"""The memory observatory: tagged device-memory ledger, pool
fragmentation telemetry, and OOM forensics
(profiler/mem_observatory.py — docs/OBSERVABILITY.md "The memory
observatory").

- the ledger, end to end: weakref tag registration (a dead owner's
  bytes drop to zero, never pinned alive by telemetry), the
  deduplicated attribution bound (attributed <= device in-use in both
  measured and ledger-fallback modes), registry eviction at MAX_TAGS
- MEASURED fragmentation on a synthetic free-list pattern: contiguous
  runs, the pow2 histogram, `1 - largest_run / free_pages`
- `kind:"memory"` schema table: the emitted record passes, each broken
  invariant is flagged by name
- OOM forensics via the `oom@train.step` fault spec: the synthetic
  RESOURCE_EXHAUSTED rides the REAL dispatch catch, dumps a debug
  bundle whose mem_state.json names the kv-pool tag as top holder,
  and re-raises DeviceOOMError naming the holders
- FleetPressure `memory_pressure`: edge-triggered on K consecutive
  low-headroom snapshots, re-armed on clear
- max_memory_allocated reconciles against the ledger; steady-state
  overhead stays within noise (calibrated best-of-3)
"""
import gc
import json
import os
import sys
import threading
import time

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import optimizer as opt
from paddle_tpu.framework import fault_injection as fi
from paddle_tpu.jit import TrainStep
from paddle_tpu.profiler import mem_observatory as mobs
from paddle_tpu.profiler import fleet_observatory as fobs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
import check_metrics_schema as cms  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_slate():
    """No tag registry, fault spec, or cadence mark may leak across
    tests (or in from the env)."""
    os.environ.pop("PADDLE_TPU_FAULT_SPEC", None)
    fi.configure("")
    mobs.reset()
    yield
    fi.configure("")
    mobs.reset()


def _validate(rec):
    return cms.validate_line(json.dumps(rec))


def _loss_fn(out, y):
    return paddle.mean(paddle.nn.functional.square_error_cost(out, y))


def _build_step(seed=0, **kw):
    paddle.seed(seed)
    m = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
    o = opt.AdamW(learning_rate=1e-2, parameters=m.parameters())
    return TrainStep(m, _loss_fn, o, **kw)


def _batch(n=16):
    rs = np.random.RandomState(0)
    return (paddle.to_tensor(rs.randn(n, 8).astype("float32")),
            paddle.to_tensor(rs.randn(n, 1).astype("float32")))


class _StubPool:
    """Paged-pool stand-in with a hand-set free list: the fragmentation
    walk and the byte gauges only touch this surface."""
    strategy = "paged"

    def __init__(self, n_pages=8, free=None, evictable=0, claims=0):
        self.n_pages = n_pages
        self.lock = threading.RLock()
        self._free = list(range(n_pages)) if free is None else list(free)
        self._evictable = evictable
        self._claims = claims
        # two device arrays: 8 pages x 32 floats = 1 KiB per array
        self.k = [jnp.zeros((n_pages, 32), jnp.float32)]
        self.v = [jnp.zeros((n_pages, 32), jnp.float32)]

    def device_arrays(self):
        return list(self.k) + list(self.v)

    def n_free_pages(self):
        return len(self._free)

    def n_evictable_pages(self):
        return self._evictable

    def outstanding_claims(self):
        return self._claims

    def pool_stats(self):
        return {"cache_strategy": "paged", "n_pages": self.n_pages,
                "free_pages": len(self._free),
                "held_pages": self.n_pages - len(self._free)}


# -- the tagged ledger ----------------------------------------------------

class TestLedger:
    def test_register_arrays_and_weakref_death(self):
        arrs = [jnp.zeros((256,), jnp.float32)]  # 1 KiB
        mobs.register_arrays("kv_pool.t", arrs)
        led = mobs.ledger()
        assert led["kv_pool.t"]["bytes"] == 1024
        assert led["kv_pool.t"]["alive"] == 1
        # telemetry must not pin the buffer: dropping the only strong
        # ref frees it, and the tag's bytes go to zero
        del arrs
        gc.collect()
        led = mobs.ledger()
        assert led["kv_pool.t"]["bytes"] == 0
        assert led["kv_pool.t"]["alive"] == 0

    def test_register_owner_with_getter_follows_replacement(self):
        class Store:
            def __init__(self):
                self.buf = jnp.zeros((256,), jnp.float32)
        s = Store()
        mobs.register("params", s, lambda o: [o.buf])
        assert mobs.ledger()["params"]["bytes"] == 1024
        # the getter runs at REPORT time: a donated/replaced store
        # reports its current buffer, not a stale snapshot
        s.buf = jnp.zeros((512,), jnp.float32)
        assert mobs.ledger()["params"]["bytes"] == 2048
        # a dead owner reports zero (and never raises)
        del s
        gc.collect()
        assert mobs.ledger()["params"]["bytes"] == 0

    def test_registry_bounded_oldest_evicted(self):
        keep = [jnp.zeros((8,), jnp.float32)]
        for i in range(mobs.MAX_TAGS + 3):
            mobs.register_arrays(f"tag{i:03d}", keep)
        tags = mobs.registered_tags()
        assert len(tags) == mobs.MAX_TAGS
        assert "tag000" not in tags and "tag002" not in tags
        assert f"tag{mobs.MAX_TAGS + 2:03d}" in tags

    def test_attribution_dedup_and_bound(self):
        shared = [jnp.zeros((256,), jnp.float32)]  # 1 KiB
        mobs.register_arrays("a", shared)
        mobs.register_arrays("b", shared)  # the SAME buffer, two tags
        rep = mobs.mem_report()
        # per-tag the buffer counts twice; the attributed total dedups
        # by buffer identity, so sharing never inflates attribution
        assert rep["tags"]["a"] == 1024 and rep["tags"]["b"] == 1024
        assert rep["attributed_bytes"] == 1024
        # THE bound, both modes: on stat-less backends (CPU) in_use is
        # pinned to the ledger, so attributed <= in_use always holds
        assert rep["attributed_bytes"] <= rep["device_bytes_in_use"]
        assert rep["unattributed_bytes"] >= 0
        if not rep["measured"]:
            assert rep["device_bytes_in_use"] == rep["attributed_bytes"]

    def test_max_memory_allocated_reconciles_with_ledger(self):
        """The bench headline's two memory numbers must agree: the
        process-wide peak (`paddle.device.max_memory_allocated` — HBM
        high-water on TPU, peak RSS on CPU) can never be smaller than
        the bytes the ledger attributes to live registered buffers."""
        big = [jnp.zeros((1 << 16,), jnp.float32)]  # 256 KiB
        mobs.register_arrays("params", big)
        rep = mobs.mem_report()
        assert rep["attributed_bytes"] == big[0].nbytes
        assert paddle.device.max_memory_allocated() \
            >= rep["attributed_bytes"]
        # and the report's own peak respects the same floor
        assert rep["device_peak_bytes"] >= rep["attributed_bytes"]


# -- measured fragmentation ----------------------------------------------

class TestFragmentation:
    def test_synthetic_free_pattern(self):
        # free [1,2,3,5,7]: runs (1-3), (5), (7) -> largest 3 of 5
        p = _StubPool(n_pages=8, free=[1, 2, 3, 5, 7])
        frag = mobs.fragmentation(p)
        assert frag["free_pages"] == 5
        assert frag["free_runs"] == 3
        assert frag["largest_free_run"] == 3
        assert frag["fragmentation"] == pytest.approx(1 - 3 / 5)
        assert frag["free_run_histogram"] == {"4": 1, "1": 2}

    def test_unbroken_run_and_empty_list(self):
        assert mobs.fragmentation(
            _StubPool(free=[2, 3, 4, 5]))["fragmentation"] == 0.0
        empty = mobs.fragmentation(_StubPool(free=[]))
        assert empty["fragmentation"] == 0.0
        assert empty["largest_free_run"] == 0

    def test_recurrent_pool_has_no_adjacency(self):
        class Rec:
            strategy = "recurrent"
        assert mobs.fragmentation(Rec()) is None

    def test_pool_hbm_page_math(self):
        p = _StubPool(n_pages=8, free=[1, 2, 3, 5, 7], evictable=1,
                      claims=2)
        hbm = mobs.pool_hbm(p)
        assert hbm["hbm_total_bytes"] == 2048  # two 1 KiB arrays
        assert hbm["page_bytes"] == 256
        assert hbm["hbm_free_bytes"] == (5 + 1) * 256
        # headroom subtracts outstanding admission claims
        assert hbm["hbm_headroom_bytes"] == (5 + 1 - 2) * 256


# -- kind:"memory" records + schema --------------------------------------

class TestMemoryRecords:
    def test_train_and_serve_records_schema_valid(self, tmp_path,
                                                  monkeypatch):
        mfile = tmp_path / "metrics.jsonl"
        monkeypatch.setenv("PADDLE_TPU_METRICS_FILE", str(mfile))
        arrs = [jnp.zeros((256,), jnp.float32)]
        mobs.register_arrays("params", arrs)
        assert mobs.record_memory(source="train", step=1) is not None
        p = _StubPool(n_pages=8, free=[1, 2, 3, 5, 7])
        mobs.register_arrays("kv_pool.e0", p.device_arrays())
        rec = mobs.record_memory(source="serve", step=2, engine="e0",
                                 cache=p)
        assert rec is not None
        lines = [json.loads(l) for l in
                 mfile.read_text().splitlines() if l.strip()]
        mems = [r for r in lines if r.get("kind") == "memory"]
        assert len(mems) == 2
        assert all(_validate(r) == [] for r in mems)
        by_src = {r["source"]: r for r in mems}
        assert by_src["train"]["tags"]["params"] == 1024
        srv = by_src["serve"]
        # serve records are SELF-CONTAINED for the gate reconciliation:
        # pool geometry and the kv tag ride in the same record
        assert srv["engine"] == "e0"
        assert srv["cache_strategy"] == "paged"
        assert srv["n_pages"] == 8 and srv["page_bytes"] == 256
        assert abs(srv["tags"]["kv_pool.e0"]
                   - srv["n_pages"] * srv["page_bytes"]) \
            <= srv["page_bytes"]
        assert srv["fragmentation"] == pytest.approx(0.4)
        # the ring carries both for host_stats / the debug bundle
        assert [r["source"] for r in mobs.records_tail()] \
            == ["train", "serve"]

    def test_cadence_first_always_then_every_n(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_MEMORY_EVERY", "4")
        assert mobs.maybe_memory(3, source="train") is not None  # first
        assert mobs.maybe_memory(5, source="train") is None
        assert mobs.maybe_memory(8, source="train") is not None
        monkeypatch.setenv("PADDLE_TPU_MEMORY_EVERY", "0")
        assert mobs.maybe_memory(16, source="train") is None  # disabled

    def test_train_step_emits_on_first_step(self, tmp_path,
                                            monkeypatch):
        mfile = tmp_path / "metrics.jsonl"
        monkeypatch.setenv("PADDLE_TPU_METRICS_FILE", str(mfile))
        step = _build_step()
        x, y = _batch()
        float(step(x, y))
        lines = [json.loads(l) for l in
                 mfile.read_text().splitlines() if l.strip()]
        mems = [r for r in lines if r.get("kind") == "memory"]
        assert mems and all(_validate(r) == [] for r in mems)
        # TrainStep registered its stores at construction: the record
        # attributes live params + optimizer state
        assert mems[0]["source"] == "train"
        assert mems[0]["tags"]["params"] > 0
        assert mems[0]["tags"]["opt_state"] > 0

    def test_load_profiler_result_exposes_memories(self, tmp_path,
                                                   monkeypatch):
        from paddle_tpu import profiler
        mfile = tmp_path / "metrics.jsonl"
        monkeypatch.setenv("PADDLE_TPU_METRICS_FILE", str(mfile))
        arrs = [jnp.zeros((8,), jnp.float32)]  # held live for the test
        mobs.register_arrays("params", arrs)
        mobs.record_memory(source="train", step=1)
        res = profiler.load_profiler_result(str(mfile))
        assert len(res.memories) == 1
        assert res.memories[0]["tags"]["params"] == 32
        assert "1 memory records" in res.summary()
        # ...and through the host_stats.json roundtrip
        monkeypatch.setenv("PADDLE_PROFILER_DIR", str(tmp_path / "prof"))
        prof = profiler.Profiler(timer_only=True)
        path = prof.export_host_stats()
        res2 = profiler.load_profiler_result(path)
        assert len(res2.memories) == 1

    def test_obs_report_renders_memory_section(self, tmp_path,
                                               monkeypatch):
        import obs_report
        mfile = tmp_path / "metrics.jsonl"
        monkeypatch.setenv("PADDLE_TPU_METRICS_FILE", str(mfile))
        mobs.register_arrays("params", [jnp.zeros((256,), jnp.float32)])
        mobs.record_memory(source="train", step=1)
        lines = [json.loads(l) for l in
                 mfile.read_text().splitlines() if l.strip()]
        text = obs_report.render(lines)
        assert "== memory ==" in text
        assert "params" in text
        assert "MISMATCH" not in text  # nothing unexplained here
        # a measured record whose unattributed bytes exceed executable
        # peaks + tolerance renders the leak line
        leak = dict(lines[-1])
        leak.update(measured=True, unattributed_bytes=1 << 30,
                    device_bytes_in_use=1 << 30,
                    executable_peak_bytes=0)
        assert "MISMATCH" in obs_report.render(lines + [leak])


def _memory_rec(**kw):
    rec = {"ts": 1754300000.0, "rank": 0, "kind": "memory",
           "source": "serve", "step": 8, "measured": True,
           "engine": "e0", "cache_strategy": "paged",
           "tags": {"kv_pool.e0": 2048, "params": 1024},
           "attributed_bytes": 3072, "unattributed_bytes": 1024,
           "device_bytes_in_use": 4096, "device_peak_bytes": 8192,
           "device_bytes_limit": 1 << 20,
           "executable_peak_bytes": 4096,
           "n_pages": 8, "free_pages": 5, "held_pages": 3,
           "hbm_total_bytes": 2048, "hbm_free_bytes": 1280,
           "hbm_headroom_bytes": 1280, "page_bytes": 256,
           "fragmentation": 0.4, "free_runs": 3,
           "largest_free_run": 3, "free_run_histogram": {"4": 1,
                                                         "1": 2}}
    rec.update(kw)
    return rec


class TestMemorySchema:
    def test_good_record_passes(self):
        assert _validate(_memory_rec()) == []

    @pytest.mark.parametrize("bad,needle", [
        (_memory_rec(source=""), "source"),
        (_memory_rec(tags={"kv_pool.e0": -1}), "tags"),
        # THE bound: attribution can never exceed the device's in-use
        (_memory_rec(attributed_bytes=8192), "attributed_bytes"),
        (_memory_rec(fragmentation=1.5), "fragmentation"),
        (_memory_rec(largest_free_run=9), "largest_free_run"),
        (_memory_rec(free_run_histogram={"4": 0}),
         "free_run_histogram"),
        (_memory_rec(hbm_free_bytes=4096), "hbm_free_bytes"),
        (_memory_rec(hbm_headroom_bytes=2000), "hbm_headroom_bytes"),
        (_memory_rec(n_pages=0), "n_pages"),
        (_memory_rec(page_bytes="256"), "page_bytes"),
        (_memory_rec(cache_strategy="magnetic"), "cache_strategy"),
        (_memory_rec(engine=""), "engine"),
    ])
    def test_rejects_bad_records(self, bad, needle):
        errs = _validate(bad)
        assert errs and any(needle in e for e in errs), (errs, needle)

    def test_recurrent_record_needs_slot_fields(self):
        rec = _memory_rec(cache_strategy="recurrent")
        for k in ("n_pages", "free_pages", "held_pages",
                  "hbm_total_bytes", "hbm_free_bytes",
                  "hbm_headroom_bytes", "page_bytes", "fragmentation",
                  "free_runs", "largest_free_run",
                  "free_run_histogram"):
            rec.pop(k)
        errs = _validate(rec)  # slot fields missing: flagged by name
        assert errs and any("free_slots" in e for e in errs)
        rec.update(free_slots=3, held_slots=5, state_bytes_total=4096)
        assert _validate(rec) == []

    def test_train_record_carries_no_pool_fields(self):
        rec = _memory_rec(source="train")
        for k in ("engine", "cache_strategy", "n_pages", "free_pages",
                  "held_pages", "hbm_total_bytes", "hbm_free_bytes",
                  "hbm_headroom_bytes", "page_bytes", "fragmentation",
                  "free_runs", "largest_free_run",
                  "free_run_histogram"):
            rec.pop(k)
        assert _validate(rec) == []


# -- OOM forensics --------------------------------------------------------

class TestOOMForensics:
    def test_is_oom_markers_and_no_double_wrap(self):
        assert mobs.is_oom(RuntimeError(
            "RESOURCE_EXHAUSTED: Out of memory while trying to "
            "allocate 8589934592 bytes"))
        assert mobs.is_oom(RuntimeError("xla OutOfMemory on device"))
        assert not mobs.is_oom(RuntimeError("shape mismatch"))
        # an already-wrapped DeviceOOMError must NOT re-wrap: the
        # message carries the markers, the type is the terminal form
        err = mobs.DeviceOOMError("device out of memory at x")
        assert not mobs.is_oom(err)

    def test_parse_requested_bytes(self):
        assert mobs.parse_requested_bytes(
            "while trying to allocate 8589934592 bytes") == 8589934592
        assert mobs.parse_requested_bytes(
            "Failed to allocate request for 2.5GiB on device") \
            == int(2.5 * 1024 ** 3)
        assert mobs.parse_requested_bytes("no sizes here") == 0

    def test_oom_fault_dumps_bundle_naming_kv_pool(self, tmp_path,
                                                   monkeypatch):
        """The acceptance drill: `oom@train.step` raises the synthetic
        RESOURCE_EXHAUSTED from INSIDE the real dispatch try-block, so
        the production catch runs end-to-end — debug bundle, the
        mem_state.json ledger naming the kv-pool tag as top holder,
        and the DeviceOOMError re-raise."""
        monkeypatch.setenv("PADDLE_TPU_DEBUG_DUMP", str(tmp_path))
        step = _build_step()  # registers params/opt_state tags
        # a kv pool 256 KiB deep dwarfs the tiny model: it MUST come
        # out as the top holder in the forensics
        kv = [jnp.zeros((1 << 16,), jnp.float32)]
        mobs.register_arrays("kv_pool.drill", kv)
        x, y = _batch()
        fi.configure("oom@train.step#1")
        with pytest.raises(mobs.DeviceOOMError) as ei:
            step(x, y)
        err = ei.value
        assert err.site == "train.step"
        assert err.requested_bytes == 8 << 30  # parsed from the message
        assert err.top_holders[0][0] == "kv_pool.drill"
        assert "kv_pool.drill" in str(err)
        # the bundle landed, and its mem_state.json tells the story
        assert err.bundle_dir and os.path.isdir(err.bundle_dir)
        payload = json.loads(
            open(os.path.join(err.bundle_dir, "mem_state.json")).read())
        assert payload["last_oom"]["site"] == "train.step"
        assert payload["last_oom"]["top_holders"][0][0] \
            == "kv_pool.drill"
        assert payload["ledger"]["kv_pool.drill"]["bytes"] == kv[0].nbytes
        # one-shot fault: the step recovers on the next dispatch
        assert np.isfinite(float(step(x, y)))

    def test_serving_ragged_step_wraps_oom(self):
        """The serving catch path: an allocator-shaped RuntimeError out
        of the ragged step surfaces as DeviceOOMError with the serve
        site (wired in inference/serving.py `_ragged_step`)."""
        e = RuntimeError("RESOURCE_EXHAUSTED: failed to allocate "
                         "request for 1.00GiB on device")
        err = mobs.oom_error(e, site="serve.ragged_step")
        assert isinstance(err, mobs.DeviceOOMError)
        assert err.site == "serve.ragged_step"
        assert mobs.mem_state()["last_oom"]["site"] == "serve.ragged_step"


# -- FleetPressure: memory_pressure edge-triggering ----------------------

class TestMemoryPressure:
    def test_edge_triggered_and_rearmed(self):
        p = fobs.FleetPressure("pr", memory_snapshots=3,
                               memory_watermark=0.1)
        low = {"saturated": [], "hbm_total_bytes": 1000,
               "hbm_headroom_bytes": 50}   # 5% < the 10% watermark
        ok = {"saturated": [], "hbm_total_bytes": 1000,
              "hbm_headroom_bytes": 500}
        for rec in (low, low):
            p.observe_snapshot(rec)
        assert len(p.events) == 0  # below K: no event yet
        p.observe_snapshot(low)
        assert [e["event"] for e in p.events] == ["memory_pressure"]
        assert p.events[-1]["hbm_headroom_bytes"] == 50
        for _ in range(5):  # a starved hour is ONE event
            p.observe_snapshot(low)
        assert len(p.events) == 1
        p.observe_snapshot(ok)  # re-arm
        for rec in (low, low, low):
            p.observe_snapshot(rec)
        assert [e["event"] for e in p.events] \
            == ["memory_pressure", "memory_pressure"]

    def test_zero_total_never_fires(self):
        # a snapshot with no byte gauges (pre-memory-observatory rank
        # logs) must not read as 100% pressure
        p = fobs.FleetPressure("pr", memory_snapshots=1)
        for _ in range(5):
            p.observe_snapshot({"saturated": []})
            p.observe_snapshot({"saturated": [], "hbm_total_bytes": 0,
                                "hbm_headroom_bytes": 0})
        assert len(p.events) == 0


# -- overhead stays within noise (PR 5 pattern) --------------------------

@pytest.mark.heavy
def test_memory_observatory_overhead_within_noise(monkeypatch):
    """Steady-state train-step wall time with the memory cadence active
    (the default every-16 gate: one int modulo + a set lookup off-
    cadence) stays within noise of the disabled path — calibrated,
    best-of-3 (2-CPU container convention)."""
    def median_step_s(every):
        monkeypatch.setenv("PADDLE_TPU_MEMORY_EVERY", every)
        mobs.reset()
        step = _build_step()
        x, y = _batch()
        for _ in range(3):
            loss = step(x, y)
        float(loss)  # warm: compile + first dispatches
        times = []
        for _ in range(20):
            t0 = time.perf_counter()
            float(step(x, y))
            times.append(time.perf_counter() - t0)
        return sorted(times)[len(times) // 2]

    for _ in range(3):
        base = median_step_s("0")
        active = median_step_s("16")
        if active <= base * 1.5 + 0.002:
            return
    raise AssertionError(
        f"memory observatory overhead out of noise after 3 rounds: "
        f"base={base * 1e3:.2f}ms active={active * 1e3:.2f}ms")
