"""fleet.metrics — cross-worker metric aggregation.

Parity: /root/reference/python/paddle/distributed/fleet/metrics/metric.py.
Each helper all-reduces locally-accumulated statistics over the worker
world via fleet.util (single-process worlds are the identity, matching
the TPU single-controller SPMD model) and then finishes the metric
math on the aggregate.
"""
import numpy as np

__all__ = []


def _resolve(value):
    from ...fleet import util
    from ....framework.core import Tensor
    if isinstance(value, Tensor):
        value = value.numpy()
    return np.asarray(value), util


def sum(input, scope=None, util=None):
    """Distributed sum of a metric array."""
    arr, u = _resolve(input)
    u = util or u
    return u.all_reduce(arr, "sum").reshape(arr.shape)


def max(input, scope=None, util=None):
    """Distributed elementwise max."""
    arr, u = _resolve(input)
    u = util or u
    return u.all_reduce(arr, "max").reshape(arr.shape)


def min(input, scope=None, util=None):
    """Distributed elementwise min."""
    arr, u = _resolve(input)
    u = util or u
    return u.all_reduce(arr, "min").reshape(arr.shape)


def auc(stat_pos, stat_neg, scope=None, util=None):
    """Distributed AUC from per-worker positive/negative score
    histograms (the reference's streaming formulation)."""
    pos, u = _resolve(stat_pos)
    neg, _ = _resolve(stat_neg)
    u = util or u
    global_pos = u.all_reduce(pos.ravel(), "sum")
    global_neg = u.all_reduce(neg.ravel(), "sum")
    num_bucket = global_pos.shape[0]
    area = 0.0
    pos_cum = 0.0
    neg_cum = 0.0
    new_pos = 0.0
    new_neg = 0.0
    for i in range(num_bucket):
        idx = num_bucket - 1 - i
        new_pos = pos_cum + global_pos[idx]
        new_neg = neg_cum + global_neg[idx]
        area += (new_neg - neg_cum) * (pos_cum + new_pos) / 2
        pos_cum = new_pos
        neg_cum = new_neg
    if pos_cum == 0 or neg_cum == 0:
        return 0.5
    return float(area / (pos_cum * neg_cum))


def mae(abserr, total_ins_num, scope=None, util=None):
    """Distributed mean absolute error from (Σ|err|, N)."""
    err, u = _resolve(abserr)
    u = util or u
    n = _as_count(total_ins_num, u)
    global_err = float(u.all_reduce(err.ravel().sum(), "sum"))
    return global_err / n


def rmse(sqrerr, total_ins_num, scope=None, util=None):
    """Distributed root-mean-square error from (Σerr², N)."""
    return float(np.sqrt(mse(sqrerr, total_ins_num, scope, util)))


def mse(sqrerr, total_ins_num, scope=None, util=None):
    """Distributed mean squared error from (Σerr², N)."""
    err, u = _resolve(sqrerr)
    u = util or u
    n = _as_count(total_ins_num, u)
    global_err = float(u.all_reduce(err.ravel().sum(), "sum"))
    return global_err / n


def acc(correct, total, scope=None, util=None):
    """Distributed accuracy from (correct, total) counts."""
    c, u = _resolve(correct)
    u = util or u
    t = _as_count(total, u)
    global_c = float(u.all_reduce(c.ravel().sum(), "sum"))
    return global_c / t


def _as_count(total, util):
    arr = np.asarray(
        total.numpy() if hasattr(total, "numpy") else total)
    n = float(util.all_reduce(arr.ravel().sum(), "sum"))
    if n == 0:
        raise ZeroDivisionError(
            "fleet.metrics: total instance count reduced to zero")
    return n
