"""paddle.save / paddle.load. Parity: python/paddle/framework/io.py.

State dicts (nested dict/list of Tensor) are converted to numpy and
pickled. Layer state_dicts, optimizer state_dicts and arbitrary nested
containers round-trip; large-model sharded checkpointing lives in
paddle_tpu.distributed (orbax-backed).
"""
import os
import pickle

import numpy as np

from .core import Tensor, Parameter

__all__ = ["save", "load"]

_PROTO = 4


def _to_serializable(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(obj.numpy(), isinstance(obj, Parameter),
                              obj.name, obj.stop_gradient)
    if isinstance(obj, dict):
        return {k: _to_serializable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_serializable(v) for v in obj)
    return obj


def _from_serializable(obj, return_numpy=False):
    if isinstance(obj, _TensorPayload):
        if return_numpy:
            return obj.array
        t = Parameter(obj.array, name=obj.name) if obj.is_param \
            else Tensor(obj.array)
        t.stop_gradient = obj.stop_gradient
        return t
    if isinstance(obj, dict):
        return {k: _from_serializable(v, return_numpy)
                for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_from_serializable(v, return_numpy) for v in obj)
    return obj


class _TensorPayload:
    def __init__(self, array, is_param, name, stop_gradient):
        self.array = np.asarray(array)
        self.is_param = is_param
        self.name = name
        self.stop_gradient = stop_gradient


def save(obj, path, protocol=_PROTO, **configs):
    if hasattr(path, "write"):  # file-like target (framework/io.py
        # doc example 5 saves into a BytesIO)
        pickle.dump(_to_serializable(obj), path, protocol=protocol)
        return
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_serializable(obj), f, protocol=protocol)


def load(path, **configs):
    if hasattr(path, "read"):
        obj = pickle.load(path)
    else:
        with open(path, "rb") as f:
            obj = pickle.load(f)
    return _from_serializable(obj, configs.get("return_numpy", False))
