"""paddle.distributed.launch. Parity: python/paddle/distributed/launch.py.

The reference spawns one process per GPU and wires NCCL endpoints. On TPU
the unit is a *host*: single-host runs need no launcher (one process owns
all local chips); multi-host (pod/DCN) runs start one process per host
with a coordinator, mapped onto jax.distributed.initialize. Usage:

    python -m paddle_tpu.distributed.launch \
        --nnodes 4 --node_rank 0 --master addr:port train.py [args...]
"""
import argparse
import os
import runpy
import sys

__all__ = ["main", "launch"]


def _parse():
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--nnodes", type=int,
                   default=int(os.environ.get("PADDLE_NNODES", "1")))
    p.add_argument("--node_rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", "0")))
    p.add_argument("--master",
                   default=os.environ.get("PADDLE_MASTER", ""))
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="kept for CLI parity; one process drives all "
                        "local TPU chips")
    p.add_argument("--devices", default=None)
    p.add_argument("--log_dir", default=None)
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args()


def launch(script, script_args=(), nnodes=1, node_rank=0, master=""):
    if nnodes > 1:
        if not master:
            raise ValueError("--master addr:port required when nnodes > 1")
        os.environ["PADDLE_TPU_COORDINATOR"] = master
        os.environ["PADDLE_TPU_NUM_PROCESSES"] = str(nnodes)
        os.environ["PADDLE_TPU_PROCESS_ID"] = str(node_rank)
    sys.argv = [script] + list(script_args)
    runpy.run_path(script, run_name="__main__")


def main():
    args = _parse()
    launch(args.training_script, args.training_script_args, args.nnodes,
           args.node_rank, args.master)


if __name__ == "__main__":
    main()
