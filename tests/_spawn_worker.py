"""Driver for test_spawn_multiprocess: paddle.distributed.spawn with
nprocs=2 on the pinned CPU backend — each rank must join a real
2-process jax.distributed world."""
import os

os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)  # exactly 1 local CPU device per proc


def train(tag):
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.distributed as dist
    print(f"{tag} rank={dist.get_rank()} world={jax.process_count()}",
          flush=True)
    assert jax.process_count() == 2


if __name__ == "__main__":
    import paddle_tpu.distributed as dist
    dist.spawn(train, args=("spawned",), nprocs=2)
