"""Speculative decoding through the fixed-shape ragged step
(docs/SERVING.md "Speculative decoding").

A small DRAFT model proposes k tokens per active sequence per
iteration; the TARGET model verifies all k+1 positions as ONE
prefill-chunk-style row through the existing `serve.ragged_step`
executable. The serving kernel already handles mixed prefill/decode
rows, and the MIN_Q_TOKENS=8 token-bucket floor means a k<=7 verify
row pads to the SAME (8, 1, W) signature a 1-token decode row does —
speculation adds zero new executables in steady state
(tools/_gate_common.py enforces this), so the speedup is pure
arithmetic: one cheap draft step per proposed token plus one
target step per k+1 positions, instead of one target step per token.

Why acceptance is an EQUALITY test, not a distribution argument: the
serving sampler keys every draw by fold_in(request_key,
absolute_position) (models/gpt.py sample_token_rows), so the token the
non-speculative engine would emit at a given position is a pure
function of (request seed, history). The verify row reads the target's
per-position sample v_j at every draft position in one step
(paged_ragged_step(return_per_token=True)); `accept_length` then takes
the longest prefix where the draft guessed those exact samples, plus
the first target sample the draft missed. By induction every emitted
token equals the non-speculative stream bit-for-bit — greedy AND
sampled — which is the whole correctness contract (no acceptance-ratio
coin flips, no distribution drift).

Rejected tails roll back the KV write cursor only
(PagedKVCache.rollback): pages, refcounts, and claims are untouched —
the admission claim already reserved worst-case prompt+max_new pages,
and copy-on-write materialized any shared page before the speculated
write, so prefix sharers never observe a rejected token. The draft
model's own PagedKVCache participates in admission as a SECOND claims
ledger (serving.py gates on both pools), so two-model admission can
never double-book either pool.
"""

from ..ops.pallas.attention_core import MIN_Q_TOKENS


class SpeculativeConfig:
    """Configuration handed to GenerationEngine(speculative=...).

    `draft_model` is a smaller model with the SAME tokenizer/vocab as
    the target (typically fewer layers); it runs its own paged cache
    and proposes `k` tokens per sequence per iteration. `k` is capped
    at MIN_Q_TOKENS - 1 so the k+1-token verify row pads into the
    already-warm (MIN_Q_TOKENS, 1, W) ragged signature — a larger k
    would mint a new executable per depth and forfeit the zero-compile
    contract.

    `draft_temperature` optionally overrides the DRAFT's sampling
    temperature (the target's acceptance draw always uses the
    request's own sampling config — this knob only shifts how often
    the draft guesses it; bench.py's accept-rate sweep varies it).
    None means the draft mirrors each request's own sampling config,
    which maximizes agreement when draft and target logits are close.

    `draft_pages` / `draft_page_size` size the draft model's page pool
    (default: same geometry as the target's)."""

    __slots__ = ("draft_model", "k", "draft_temperature",
                 "draft_pages", "draft_page_size")

    def __init__(self, draft_model, k=4, draft_temperature=None,
                 draft_pages=None, draft_page_size=None):
        k = int(k)
        if not 1 <= k <= MIN_Q_TOKENS - 1:
            raise ValueError(
                f"SpeculativeConfig k={k} out of range [1, "
                f"{MIN_Q_TOKENS - 1}]: the k+1-token verify row must "
                f"fit the MIN_Q_TOKENS={MIN_Q_TOKENS} token bucket or "
                "speculation would mint new executables")
        if draft_model is None:
            raise ValueError("SpeculativeConfig requires a draft model")
        self.draft_model = draft_model
        self.k = k
        self.draft_temperature = (None if draft_temperature is None
                                  else float(draft_temperature))  # hot-sync-ok: construction-time host float, not a device read
        self.draft_pages = draft_pages
        self.draft_page_size = draft_page_size


def accept_length(draft_tokens, verify_samples):
    """Accepted-token count m for one verify row.

    `draft_tokens` is [d_1..d_j] (the j <= k tokens the draft
    proposed); `verify_samples` is [v_0..v_j] (the target's
    position-keyed sample after consuming each of the row's j+1
    tokens, read from the per-token lane of the ragged step).

    m = 1 + the longest prefix where d_{i+1} == v_i: v_0 is
    unconditionally correct (it is sampled from the true history), and
    each subsequent v_i is correct exactly when every earlier draft
    token matched — i.e. when the KV the target wrote for it came from
    the real stream. m == j+1 accepts every draft token AND the bonus
    sample v_j (the draft's reward for a perfect guess: j+1 tokens
    from one target step). The emitted tokens are verify_samples[:m],
    bit-identical to the non-speculative stream by induction."""
    if len(verify_samples) != len(draft_tokens) + 1:
        raise ValueError(
            f"verify_samples has {len(verify_samples)} entries for "
            f"{len(draft_tokens)} draft tokens; expected one per "
            "consumed row token (drafts + the anchor)")
    m = 1
    for d, v in zip(draft_tokens, verify_samples):
        if int(d) != int(v):
            break
        m += 1
    return m
