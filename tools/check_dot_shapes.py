#!/usr/bin/env python
"""Dot-shape gate: attention score dots must be MXU-shaped.

Why (ISSUE 16; *Ragged Paged Attention*, arxiv 2604.15464): the TPU
MXU is a 128x128 systolic array fed by (8, 128) f32 tiles — a dot
whose M dimension is below 8 pads the sublane dimension with zeros and
runs at a fraction of peak no matter what the kernel around it does.
The seed-era serving kernel's per-(token, head) `[1, D] x [D, P]`
score dots were exactly this shape. This gate turns "MXU-shaped" from
a claim in a docstring into a ratchet: it lowers BOTH Pallas attention
kernels (serving ragged + training flash) at the canonical gate
geometries, parses every `stablehlo.dot_general` in the lowered
modules, and FAILS if any rank-2 dot result has M < MIN_DOT_ROWS — or
if a module contains no dots at all (a parse that finds nothing must
not pass vacuously).

It also checks the PLANNER side of the contract: the serving engine's
token-bucket rule (pad_t >= MIN_Q_TOKENS) composed with
attention_core.choose_q_block must yield q-block rows >= MIN_DOT_ROWS
for every bucket warm_async can emit — the kernel being capable of
MXU shapes is worthless if the scheduler feeds it 1-token buckets.

Kernels are lowered in Pallas interpret mode (their dots inline into
the StableHLO with their real shapes), so the gate runs on the same
CPU containers as tier-1 (tests/test_attention_blocking.py runs it).

Usage:
  python tools/check_dot_shapes.py [--min-rows 8] [-v]
Exit 0 clean, 1 on a narrow dot, 2 on gate failure.
"""
import argparse
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

_DOT = re.compile(
    r"stablehlo\.dot_general.*->\s*tensor<([0-9x]+)x[a-z0-9]+>")


def dot_result_dims(stablehlo_text):
    """All dot_general result shapes (tuples of ints) in a lowered
    module's StableHLO text."""
    return [tuple(int(d) for d in m.group(1).split("x"))
            for m in _DOT.finditer(stablehlo_text)]


def check_module(name, text, min_rows):
    """(violations, n_dots) for one lowered module: every rank-2 dot's
    M (first result dim) must reach min_rows. Rank-3+ dots carry batch
    dims; their M is the second-to-last dim."""
    violations = []
    dims = dot_result_dims(text)
    if not dims:
        violations.append(
            f"{name}: no stablehlo.dot_general found in the lowered "
            "module — the parse found nothing to check (lowering or "
            "regex drift); the gate must not pass vacuously")
    for shape in dims:
        m = shape[-2] if len(shape) >= 2 else 1
        if m < min_rows:
            violations.append(
                f"{name}: dot_general result {'x'.join(map(str, shape))} "
                f"has M={m} < {min_rows} — a VPU-shaped score dot is "
                "back; check choose_q_block / head folding and the "
                "serving token-bucket floor")
    return violations, len(dims)


def lower_ragged_kernel():
    """Lower serve.ragged_step's attention kernel standalone at the
    canonical gate geometry (tools/_gate_common.py emit_workload: GPT
    hidden 32 / 2 heads -> D=16, page_size 16, the floored (8, 1, 1)
    signature)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas.paged_attention import \
        ragged_paged_attention

    T, H, KVH, D = 8, 2, 2, 16
    n_pages, P, B, W = 8, 16, 1, 1
    sds = jax.ShapeDtypeStruct
    fn = jax.jit(lambda *a: ragged_paged_attention(*a, interpret=True))
    lowered = fn.lower(
        sds((T, H, D), jnp.float32),
        sds((n_pages, P, KVH, D), jnp.float32),
        sds((n_pages, P, KVH, D), jnp.float32),
        sds((B, W), jnp.int32), sds((T,), jnp.int32),
        sds((T,), jnp.int32))
    return lowered.as_text()


def lower_flash_kernel():
    """Lower the training flash kernel (fwd) standalone at the
    canonical train-step geometry (batch 2, seq 16, 2 heads, D=16)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas.flash_attention import \
        flash_attention_arrays

    B, T, H, D = 2, 16, 2, 16
    sds = jax.ShapeDtypeStruct
    fn = jax.jit(lambda q, k, v: flash_attention_arrays(
        q, k, v, causal=True, interpret=True))
    x = sds((B, T, H, D), jnp.float32)
    return fn.lower(x, x, x).as_text()


def check_planner(min_rows):
    """The serving bucket rule must deliver q-blocks >= min_rows for
    every T bucket the engine can pad to (pow2 floored at
    MIN_Q_TOKENS, up to a generous prefill-chunk ceiling)."""
    from paddle_tpu.ops.pallas.attention_core import (
        MIN_Q_TOKENS, MXU_ROWS, choose_q_block)
    violations = []
    if MIN_Q_TOKENS < min_rows:
        violations.append(
            f"planner: MIN_Q_TOKENS={MIN_Q_TOKENS} < {min_rows} — the "
            "serving pad floor no longer guarantees MXU-shaped blocks")
    t = MIN_Q_TOKENS
    while t <= 4096:  # every pow2 bucket a prefill chunk can land on
        bq = choose_q_block(t, cap=MXU_ROWS)
        if bq < min_rows:
            violations.append(
                f"planner: T bucket {t} yields q_block {bq} < "
                f"{min_rows}")
        t *= 2
    return violations


def check_verify_rows(min_rows):
    """Speculative verify-row geometry (inference/speculative.py): a
    k-draft verify row carries k+1 tokens, and SpeculativeConfig caps
    k at MIN_Q_TOKENS - 1 precisely so that every legal depth pads
    into the (MIN_Q_TOKENS, ...) token bucket — the same warm decode
    signature, still MXU-shaped. Walk every legal k and assert the
    padded bucket and its q-block both hold, so a future change to the
    k cap, the pad floor, or choose_q_block cannot silently ship
    sub-tile verify dots (or mint per-depth executables)."""
    from paddle_tpu.ops.pallas.attention_core import (
        MIN_Q_TOKENS, MXU_ROWS, choose_q_block)

    def pow2(n):
        p = 1
        while p < n:
            p *= 2
        return p

    violations = []
    for k in range(1, MIN_Q_TOKENS):  # every legal SpeculativeConfig.k
        t = max(pow2(k + 1), MIN_Q_TOKENS)  # the engine's pad rule
        if t != MIN_Q_TOKENS:
            violations.append(
                f"verify-row: k={k} ({k + 1} tokens) pads to bucket "
                f"{t} != MIN_Q_TOKENS {MIN_Q_TOKENS} — speculation "
                "would mint a new executable per depth")
        bq = choose_q_block(t, cap=MXU_ROWS)
        if bq < min_rows:
            violations.append(
                f"verify-row: k={k} bucket {t} yields q_block {bq} < "
                f"{min_rows} — sub-MXU verify dots")
    return violations


def main(argv=None):
    ap = argparse.ArgumentParser(
        "check_dot_shapes",
        description="attention score dots must have M >= the MXU "
                    "sublane tile")
    ap.add_argument("--min-rows", type=int, default=int(
        os.environ.get("PADDLE_TPU_MIN_DOT_ROWS", "8")))
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        modules = [("serve.ragged_step/paged_attention",
                    lower_ragged_kernel()),
                   ("train.step/flash_attention", lower_flash_kernel())]
    except Exception as e:  # lowering itself broke: gate failure
        print(f"check_dot_shapes: lowering failed: {e}", file=sys.stderr)
        return 2

    violations = []
    for name, text in modules:
        v, n = check_module(name, text, args.min_rows)
        violations += v
        print(f"{name}: {n} dot(s), "
              f"{'FAIL' if v else f'all M >= {args.min_rows}'}")
        if args.verbose:
            for shape in dot_result_dims(text):
                print(f"  dot -> {'x'.join(map(str, shape))}")
    violations += check_planner(args.min_rows)
    violations += check_verify_rows(args.min_rows)
    for v in violations:
        print(f"FAIL: {v}")
    if violations:
        print(f"FAIL: {len(violations)} narrow-dot violation(s)")
        return 1
    print(f"OK: every attention dot is MXU-shaped "
          f"(M >= {args.min_rows})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
