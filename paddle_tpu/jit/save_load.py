"""paddle.jit.save / load.

Parity: python/paddle/fluid/dygraph/jit.py:save + io.py:TranslatedLayer.
TPU-native format: instead of a ProgramDesc proto + LoDTensor params
(`__model__` + `*.pdiparams`), we serialize the traced computation as
portable StableHLO bytes via jax.export plus a pickled numpy state dict:

    <path>.pdmodel   — serialized StableHLO (jax.export.Exported bytes)
    <path>.pdiparams — pickled {name: ndarray} state
    <path>.meta      — input specs / structure

The exported artifact is exactly what Paddle Inference loads (see
paddle_tpu/inference), and runs on any PjRt backend.
"""
import os
import pickle

import numpy as np
import jax
import jax.numpy as jnp
from jax import export as jax_export

from ..framework.core import Tensor, no_grad
from .api import StaticFunction, functional_call, state_arrays

__all__ = ["save", "load", "TranslatedLayer", "InputSpec"]


class InputSpec:
    """Parity: python/paddle/static/input.py:InputSpec."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name

    _sym_counter = [0]

    def to_shape_dtype(self):
        from ..framework.dtype import convert_dtype
        dims = []
        for s in self.shape:
            if s is None or s == -1:
                # dynamic axis → jax.export symbolic dimension, so the
                # serialized StableHLO stays batch-polymorphic
                InputSpec._sym_counter[0] += 1
                dims.append(f"_pd_b{InputSpec._sym_counter[0]}")
            else:
                dims.append(str(int(s)))
        if any(d.startswith("_pd_b") for d in dims):
            shape = jax_export.symbolic_shape(",".join(dims))
        else:
            shape = tuple(int(d) for d in dims)
        return jax.ShapeDtypeStruct(shape, convert_dtype(self.dtype))


def save(layer, path, input_spec=None, **configs):
    from ..nn.layer.layers import Layer
    if isinstance(layer, StaticFunction):
        layer = layer.wrapped
    if not isinstance(layer, Layer):
        raise TypeError("jit.save expects a Layer (or converted Layer)")
    if input_spec is None:
        raise ValueError("jit.save requires input_spec on first save")

    params, buffers = state_arrays(layer)
    specs = [s.to_shape_dtype() if isinstance(s, InputSpec)
             else jax.ShapeDtypeStruct(tuple(s.shape),
                                       s.value.dtype) for s in input_spec]

    def pure(params, buffers, *xs):
        return functional_call(layer, params, buffers, xs, training=False,
                               convert=True)

    exported = jax_export.export(jax.jit(pure))(
        jax.tree.map(lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                     params),
        jax.tree.map(lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                     buffers),
        *specs)
    blob = exported.serialize()

    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path + ".pdmodel", "wb") as f:
        f.write(blob)
    state = {"params": {k: np.asarray(v) for k, v in params.items()},
             "buffers": {k: np.asarray(v) for k, v in buffers.items()}}
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump(state, f, protocol=4)
    meta = {"input_specs": [(tuple(str(d) for d in s.shape), str(s.dtype))
                            for s in specs]}
    with open(path + ".meta", "wb") as f:
        pickle.dump(meta, f, protocol=4)


class TranslatedLayer:
    """A loaded inference computation. Callable like the original Layer."""

    def __init__(self, exported, params, buffers, meta):
        self._exported = exported
        self._params = {k: jnp.asarray(v) for k, v in params.items()}
        self._buffers = {k: jnp.asarray(v) for k, v in buffers.items()}
        self._meta = meta
        self._call = jax.jit(exported.call)

    def __call__(self, *args):
        arrays = [a.value if isinstance(a, Tensor) else jnp.asarray(a)
                  for a in args]
        out = self._call(self._params, self._buffers, *arrays)
        return jax.tree.map(Tensor, out)

    forward = __call__

    def eval(self):
        return self

    def train(self):
        raise RuntimeError("TranslatedLayer is inference-only")

    def parameters(self):
        return [Tensor(v) for v in self._params.values()]

    def state_dict(self):
        out = {k: Tensor(v) for k, v in self._params.items()}
        out.update({k: Tensor(v) for k, v in self._buffers.items()})
        return out


def load(path, **configs):
    with open(path + ".pdmodel", "rb") as f:
        exported = jax_export.deserialize(bytearray(f.read()))
    with open(path + ".pdiparams", "rb") as f:
        state = pickle.load(f)
    meta = {}
    if os.path.exists(path + ".meta"):
        with open(path + ".meta", "rb") as f:
            meta = pickle.load(f)
    return TranslatedLayer(exported, state["params"], state["buffers"],
                           meta)
