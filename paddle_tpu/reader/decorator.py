"""Reader decorators: composable generator transforms over "reader
creators" (zero-arg callables returning iterables of samples).

Parity: /root/reference/python/paddle/reader/decorator.py. Implemented
fresh on queues/threads; the multiprocess variant uses
multiprocessing.Queue rather than the reference's raw-pipe protocol —
same semantics (interleaved samples, workers end with a sentinel).
"""
import itertools
import multiprocessing
import queue
import random
import threading

__all__ = []


def cache(reader):
    """Materialize `reader`'s samples once; replay from memory after."""
    all_data = tuple(reader())

    def cache_reader():
        return iter(all_data)

    return cache_reader


def map_readers(func, *readers):
    """Yield func(*samples) over the zip of several readers."""
    def reader():
        rs = [r() for r in readers]
        for items in zip(*rs):
            yield func(*items)

    return reader


def shuffle(reader, buf_size):
    """Buffered shuffle: fill a window of buf_size samples, emit it
    shuffled, repeat."""
    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if len(buf) > 0:
            random.shuffle(buf)
            for b in buf:
                yield b

    return data_reader


def chain(*readers):
    """Concatenate readers back to back (one epoch each)."""
    def reader():
        return itertools.chain(*[r() for r in readers])

    return reader


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, **kwargs):
    """Zip readers into combined samples: (a, b1, b2) from a() and
    b() yielding tuples get flattened into one tuple per sample."""
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        if isinstance(x, tuple):
            return x
        return (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum(list(map(make_tuple, outputs)), ())
        else:
            for outputs in itertools.zip_longest(*rs):
                for o in outputs:
                    if o is None:
                        raise ComposeNotAligned(
                            "outputs of readers are not aligned.")
                yield sum(list(map(make_tuple, outputs)), ())

    return reader


def buffered(reader, size):
    """Read ahead up to `size` samples in a background thread."""
    class _End:
        pass

    def read_worker(r, q):
        for d in r:
            q.put(d)
        q.put(_End())

    def data_reader():
        r = reader()
        q = queue.Queue(maxsize=size)
        t = threading.Thread(target=read_worker, args=(r, q))
        t.daemon = True
        t.start()
        e = q.get()
        while not isinstance(e, _End):
            yield e
            e = q.get()

    return data_reader


def firstn(reader, n):
    """Limit the reader to its first n samples."""
    def firstn_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item

    return firstn_reader


class XmapEndSignal:
    pass


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Apply `mapper` to samples with `process_num` worker threads;
    `order=True` preserves input order via sequence tagging."""
    end = XmapEndSignal()

    def read_worker(r, in_q):
        for i in r():
            in_q.put(i)
        in_q.put(end)

    def order_read_worker(r, in_q):
        for order_id, sample in enumerate(r()):
            in_q.put((order_id, sample))
        in_q.put(end)

    def handle_worker(in_q, out_q, fn):
        sample = in_q.get()
        while not isinstance(sample, XmapEndSignal):
            out_q.put(fn(sample))
            sample = in_q.get()
        in_q.put(end)
        out_q.put(end)

    order_cond = threading.Condition()

    def order_handle_worker(in_q, out_q, fn, out_order):
        ins = in_q.get()
        while not isinstance(ins, XmapEndSignal):
            order_id, sample = ins
            result = fn(sample)
            with order_cond:
                while order_id != out_order[0]:
                    order_cond.wait()
                out_q.put(result)
                out_order[0] += 1
                order_cond.notify_all()
            ins = in_q.get()
        in_q.put(end)
        out_q.put(end)

    def xreader():
        in_q = queue.Queue(buffer_size)
        out_q = queue.Queue(buffer_size)
        out_order = [0]
        target = order_read_worker if order else read_worker
        t = threading.Thread(target=target, args=(reader, in_q))
        t.daemon = True
        t.start()
        args = ((in_q, out_q, mapper, out_order) if order
                else (in_q, out_q, mapper))
        target = order_handle_worker if order else handle_worker
        workers = []
        for _ in range(process_num):
            w = threading.Thread(target=target, args=args)
            w.daemon = True
            w.start()
            workers.append(w)
        finish = 0
        while finish < process_num:
            sample = out_q.get()
            if isinstance(sample, XmapEndSignal):
                finish += 1
            else:
                yield sample

    return xreader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Interleave samples from several readers, each run in its own OS
    process (CPU-bound decode work escapes the GIL)."""
    if len(readers) < 1:
        raise ValueError("multiprocess_reader needs at least one reader")

    def _worker(r, q):
        try:
            for sample in r():
                if sample is None:
                    raise ValueError("sample has None")
                q.put(sample)
        finally:
            q.put(None)

    def reader():
        q = multiprocessing.Queue(queue_size)
        procs = [multiprocessing.Process(target=_worker, args=(r, q))
                 for r in readers]
        for p in procs:
            p.daemon = True
            p.start()
        finished = 0
        while finished < len(readers):
            sample = q.get()
            if sample is None:
                finished += 1
            else:
                yield sample
        for p in procs:
            p.join()

    return reader
