"""paddle.dataset.imikolov — PTB language-model corpus, legacy reader
API.

Parity: /root/reference/python/paddle/dataset/imikolov.py
(simple-examples.tgz; NGRAM samples are n-tuples of word ids, SEQ
samples are <s> ... <e> id lists).
"""
import collections
import os
import tarfile

from .common import DATA_HOME

__all__ = []


class DataType:
    NGRAM = 1
    SEQ = 2


TRAIN_FILE = "./simple-examples/data/ptb.train.txt"
TEST_FILE = "./simple-examples/data/ptb.valid.txt"


def _tar_path():
    return os.path.join(DATA_HOME, "imikolov", "simple-examples.tgz")


def word_count(f, word_freq=None):
    if word_freq is None:
        word_freq = collections.defaultdict(int)
    for line in f:
        for w in line.strip().split():
            word_freq[w] += 1
        word_freq["<s>"] += 1
        word_freq["<e>"] += 1
    return word_freq


def build_dict(min_word_freq=50):
    """Word → id over the train corpus, frequency-ordered; <unk> gets
    the last id."""
    with tarfile.open(_tar_path()) as tf:
        train_f = [l.decode() for l in tf.extractfile(TRAIN_FILE)]
        test_f = [l.decode() for l in tf.extractfile(TEST_FILE)]
        word_freq = word_count(test_f, word_count(train_f))
        if "<unk>" in word_freq:
            word_freq["<unk>"] = -1  # re-added below with the last id
        word_freq = [x for x in word_freq.items()
                     if x[1] > min_word_freq]
        word_freq_sorted = sorted(word_freq, key=lambda x: (-x[1], x[0]))
        words, _ = list(zip(*word_freq_sorted))
        word_idx = dict(list(zip(words, range(len(words)))))
        word_idx["<unk>"] = len(words)
    return word_idx


def reader_creator(filename, word_idx, n, data_type):
    def reader():
        with tarfile.open(_tar_path()) as tf:
            f = tf.extractfile(filename)
            UNK = word_idx["<unk>"]
            for line in f:
                if DataType.NGRAM == data_type:
                    assert n > -1, "Invalid gram length"
                    line = ["<s>"] + line.decode().strip().split() + ["<e>"]
                    if len(line) >= n:
                        line = [word_idx.get(w, UNK) for w in line]
                        for i in range(n, len(line) + 1):
                            yield tuple(line[i - n:i])
                elif DataType.SEQ == data_type:
                    line = line.decode().strip().split()
                    line = [word_idx.get(w, UNK) for w in line]
                    src_seq = [word_idx["<s>"]] + line
                    trg_seq = line + [word_idx["<e>"]]
                    if n > 0 and len(src_seq) > n:
                        continue
                    yield src_seq, trg_seq
                else:
                    assert False, "Unknown data type"

    return reader


def train(word_idx, n, data_type=DataType.NGRAM):
    return reader_creator(TRAIN_FILE, word_idx, n, data_type)


def test(word_idx, n, data_type=DataType.NGRAM):
    return reader_creator(TEST_FILE, word_idx, n, data_type)


def fetch():
    from .common import download
    download("https://dataset.bj.bcebos.com/imikolov%2Fsimple-examples.tgz",
             "imikolov", None)
