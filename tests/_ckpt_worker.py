"""Worker for test_fault_tolerance.py kill-and-resume drills:
deterministic training under ElasticController/CheckpointManager in two
step flavors —

    python _ckpt_worker.py <single|hybrid> <target_step> <ckpt_dir> <out.json>

Train (resuming from the newest verified checkpoint when one exists) to
`target_step`, checkpointing every CKPT_SAVE_EVERY (default 2) steps,
then dump {"start", "losses", "digest", "step"} to out.json. The digest
is a sha256 over EVERY state leaf's raw bytes (params + optimizer state
+ scaler state + step counter), so "bit-identical resume" is literal.

Faults are injected by the PARENT via PADDLE_TPU_FAULT_SPEC (e.g.
`kill@ckpt.write#15` → SIGKILL while the background writer streams the
second checkpoint's shards): this worker needs no fault-specific code —
which is the point of the harness (framework/fault_injection.py).

The model is dropout-free so the loss trajectory is a pure function of
(params, opt state, scaler state, step) — exact replay is the
assertion. The single-step flavor carries a GradScaler so scaler state
rides the checkpoint too.
"""
import json
import os
import sys

os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PADDLE_TPU_COMPILE_CACHE"] = "0"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def build(flavor):
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu import optimizer as opt

    paddle.seed(0)
    m = nn.Sequential(nn.Linear(16, 32), nn.Tanh(), nn.Linear(32, 1))
    o = opt.AdamW(learning_rate=1e-2, parameters=m.parameters())

    def loss_fn(out, y):
        return paddle.mean(paddle.nn.functional.square_error_cost(out, y))

    if flavor == "hybrid":
        from paddle_tpu.distributed import fleet
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs["dp_degree"] = 4
        strategy.hybrid_configs["mp_degree"] = 2
        fleet.init(is_collective=True, strategy=strategy)
        step = fleet.build_train_step(m, loss_fn, o)
    else:
        from paddle_tpu.jit import TrainStep
        scaler = paddle.amp.GradScaler(init_loss_scaling=2.0 ** 10,
                                       incr_every_n_steps=3)
        step = TrainStep(m, loss_fn, o, scaler=scaler)
    rs = np.random.RandomState(0)
    X = rs.randn(32, 16).astype("float32")
    Y = (X @ rs.randn(16, 1)).astype("float32")
    return step, paddle.to_tensor(X), paddle.to_tensor(Y)


def digest(step):
    """sha256 over every state leaf's raw bytes + the step counter."""
    import hashlib
    from jax.tree_util import tree_flatten_with_path, keystr
    h = hashlib.sha256()
    for p, leaf in tree_flatten_with_path(step.tree_state())[0]:
        h.update(keystr(p).encode())
        h.update(np.asarray(jax.device_get(leaf)).tobytes())
    h.update(str(int(step._step_i)).encode())
    return h.hexdigest()


def main():
    flavor, target, ckpt_dir, out_path = (
        sys.argv[1], int(sys.argv[2]), sys.argv[3], sys.argv[4])
    save_every = int(os.environ.get("CKPT_SAVE_EVERY", "2"))
    from paddle_tpu.distributed.elastic import ElasticController

    step, X, Y = build(flavor)
    ctl = ElasticController(step, ckpt_dir, save_every_steps=save_every,
                            watchdog_timeout_s=3600)
    start = ctl.maybe_resume()
    losses = {}
    i = start
    while i < target:
        loss = float(step(X, Y))
        i = int(step._step_i)
        losses[i] = loss
        ctl.on_step()
    # drain the background writer: an injected kill mid-write fires
    # HERE at the latest (the process dies before reporting — exactly
    # the preemption the resume run must recover from)
    ctl.wait()
    ctl.stop()
    with open(out_path, "w") as f:
        json.dump({"start": start, "losses": losses,
                   "digest": digest(step), "step": int(step._step_i)}, f)


if __name__ == "__main__":
    main()
