"""Host-side training-health anomaly detectors.

The in-graph half lives in the train steps (`jit/api.py`
`TrainStep(monitor_health=True)` computes global grad norm, param norm,
and update ratio INSIDE the compiled step and returns them through the
deferred async path — no new host syncs). This module is the host half:
once those scalars land (is_ready-gated, never blocking the step loop),
`AnomalyDetector.observe()` runs cheap streaming checks and emits
structured `kind:"event"` records into the metrics JSONL, the metrics
registry (`health.anomalies` counter), and the flight recorder — so a
loss spike at step 40312 is in the ring when the crash dump fires at
step 40319, and in the Perfetto timeline as an instant marker.

Detectors (all windowed, all O(1) per step):

- **loss_spike / grad_norm_spike** — value > `spike_factor` × the
  trailing-window median (armed after `min_history` finite samples);
- **loss_nonfinite / grad_norm_nonfinite** — NaN/Inf the moment it
  lands (the async-path replacement for a per-step `check_numerics`);
- **found_inf_streak** — the GradScaler skipped `streak` consecutive
  updates (scale is collapsing faster than it can adapt);
- **retrace_storm** — ≥ `retrace_threshold` fresh compiles within the
  last `retrace_window` observed steps (shape instability: every
  retrace is a multi-second stall and a new executable);
- **straggler** — `observe_ranks()` (fed by the distributed
  observatory's rank-0 gather, `dist_observatory.py`): a rank whose
  step-time p50 exceeds `straggler_factor` × the group median by more
  than `straggler_min_lag_s` emits an event naming the rank and its
  lag — the cross-rank skew alarm a synchronous SPMD program turns
  into everyone's slowdown.

Spike and straggler events re-arm only after the signal returns below
threshold, so a level shift emits ONE event, not one per step.
"""
import collections
import math

from . import flight_recorder
from . import monitor

__all__ = ["AnomalyDetector"]


def _finite(v):
    return isinstance(v, (int, float)) and math.isfinite(v)


class AnomalyDetector:
    """Streaming anomaly checks over per-step health scalars. One
    instance per train step object; `observe()` returns the events it
    emitted for that step (also queued on `.events`)."""

    def __init__(self, window=64, spike_factor=10.0, min_history=8,
                 found_inf_streak=4, retrace_window=20,
                 retrace_threshold=3, straggler_factor=1.5,
                 straggler_min_lag_s=0.05):
        self.window = int(window)
        self.spike_factor = float(spike_factor)
        self.min_history = int(min_history)
        self.found_inf_streak = int(found_inf_streak)
        self.retrace_window = int(retrace_window)
        self.retrace_threshold = int(retrace_threshold)
        self._hist = {"loss": collections.deque(maxlen=self.window),
                      "grad_norm": collections.deque(maxlen=self.window)}
        self._spiking = {"loss": False, "grad_norm": False}
        self._inf_streak = 0
        self._retraces = collections.deque(maxlen=self.retrace_window)
        self._storming = False
        self.straggler_factor = float(straggler_factor)
        self.straggler_min_lag_s = float(straggler_min_lag_s)
        self._rank_straggling = {}  # rank -> bool (edge-triggering)
        self.events = []

    # -- emission --------------------------------------------------------
    def _emit(self, etype, step, **fields):
        rec = {"event": etype, "step": int(step)}
        rec.update(fields)
        monitor.counter("health.anomalies").inc()
        # record_event lands the record in the events ring AND (when
        # configured) the metrics JSONL — one emission point, no dup line
        flight_recorder.record_event(**rec)
        self.events.append(rec)
        return rec

    def drain(self):
        """Pop and return the accumulated events (hapi's callback feed)."""
        out, self.events = self.events, []
        return out

    # -- checks ----------------------------------------------------------
    def _check_spike(self, key, value, step, out):
        hist = self._hist[key]
        if not _finite(value):
            out.append(self._emit(f"{key}_nonfinite", step,
                                  value=repr(value)))
            return
        spiking = False
        if len(hist) >= self.min_history:
            med = sorted(hist)[len(hist) // 2]
            floor = max(abs(med), 1e-12)
            if value > self.spike_factor * floor:
                spiking = True
                if not self._spiking[key]:  # edge-triggered
                    out.append(self._emit(
                        f"{key}_spike", step, value=float(value),
                        median=float(med),
                        threshold=float(self.spike_factor * floor)))
        self._spiking[key] = spiking
        if not spiking:  # a spike must not poison its own baseline
            hist.append(float(value))

    def observe_ranks(self, step, rank_times):
        """Feed one gathered view of per-rank step times ({rank:
        step-time p50 seconds} — the distributed observatory's rank-0
        gather calls this at rankstat cadence). A rank whose time
        exceeds `straggler_factor` × the group median by more than
        `straggler_min_lag_s` emits ONE edge-triggered
        `event:"straggler"` naming the rank, its time, the median, and
        the lag; the event re-arms only after the rank returns below
        threshold. Returns the events emitted now."""
        out = []
        vals = sorted(v for v in rank_times.values() if _finite(v))
        if len(vals) < 2:
            return out
        # TRUE median (middle pair averaged for even counts): the
        # upper-middle pick would hand a 2-rank world's straggler its
        # own time as the baseline, making it structurally undetectable
        mid = len(vals) // 2
        med = vals[mid] if len(vals) % 2 else \
            0.5 * (vals[mid - 1] + vals[mid])
        floor = max(med * self.straggler_factor,
                    med + self.straggler_min_lag_s)
        for rank, v in sorted(rank_times.items()):
            lagging = _finite(v) and v > floor
            if lagging and not self._rank_straggling.get(rank, False):
                # field name straggler_rank, NOT rank: the exported
                # event record's `rank` is the EMITTING process (rank
                # 0, the gatherer) and must not be clobbered
                out.append(self._emit(
                    "straggler", step, straggler_rank=int(rank),
                    step_time_s=float(v), median_s=float(med),
                    lag_s=float(v - med),
                    world=len(rank_times)))
            self._rank_straggling[rank] = lagging
        return out

    def observe(self, step, values, retraces=None):
        """Feed one step's resolved health scalars (dict with any of
        loss / grad_norm / found_inf) plus the step object's cumulative
        retrace counter. Returns the list of events emitted NOW."""
        out = []
        for key in ("loss", "grad_norm"):
            if key in values and values[key] is not None:
                self._check_spike(key, values[key], step, out)

        fi = values.get("found_inf")
        if fi is not None:
            if _finite(fi) and fi >= 0.5:
                self._inf_streak += 1
                if self._inf_streak == self.found_inf_streak:
                    out.append(self._emit(
                        "found_inf_streak", step,
                        streak=self._inf_streak))
            else:
                self._inf_streak = 0

        if retraces is not None:
            self._retraces.append(int(retraces))
            fresh = self._retraces[-1] - self._retraces[0]
            if len(self._retraces) >= 2 and \
                    fresh >= self.retrace_threshold:
                if not self._storming:
                    self._storming = True
                    out.append(self._emit(
                        "retrace_storm", step, retraces=fresh,
                        window_steps=len(self._retraces)))
            else:
                self._storming = False
        return out
