"""paddle.jit namespace. Parity: python/paddle/jit/__init__.py."""
from .api import to_static, not_to_static, TrainStep, functional_call, \
    StaticFunction, DeferredLoss
from . import warm
from .warm import WarmHandle
from .save_load import save, load, TranslatedLayer, InputSpec
from .debug import TracedLayer, ProgramTranslator, set_code_level, \
    set_verbosity, get_code_level, get_verbosity
from . import dy2static
from .dy2static import enable_to_static

declarative = to_static
