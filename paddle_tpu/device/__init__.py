"""Device management. Parity: python/paddle/device/__init__.py.

The reference dispatches over Places (CPUPlace/CUDAPlace/XPUPlace...,
paddle/fluid/platform/place.h); here the device set is whatever JAX
exposes (TPU chips, or CPU with --xla_force_host_platform_device_count for
sharding tests). There is no per-op placement: XLA owns placement, and
multi-device execution goes through jax.sharding (see distributed/).
"""
import jax

__all__ = ["set_device", "get_device", "get_all_devices", "device_count",
           "is_compiled_with_cuda", "is_compiled_with_rocm",
           "is_compiled_with_xpu", "is_compiled_with_npu",
           "is_compiled_with_tpu", "synchronize", "get_device_properties",
           "cuda", "Stream", "Event",
           "max_memory_allocated", "memory_allocated",
           "max_memory_reserved", "memory_reserved"]

_current = None


def _default_device():
    return jax.devices()[0]


def set_device(device):
    global _current
    if isinstance(device, str):
        name = device.split(":")[0]
        idx = int(device.split(":")[1]) if ":" in device else 0
        if name in ("gpu", "cuda", "tpu", "xpu", "npu"):
            devs = jax.devices()
        elif name == "cpu":
            devs = [d for d in jax.devices() if d.platform == "cpu"] or \
                jax.devices("cpu")
        else:
            devs = jax.devices()
        _current = devs[idx % len(devs)]
    else:
        _current = device
    return _current


def get_device():
    d = _current or _default_device()
    plat = "tpu" if d.platform in ("tpu", "axon") else d.platform
    return f"{plat}:{d.id}"


def get_all_devices():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def device_count():
    return jax.device_count()


def is_compiled_with_cuda():
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_npu():
    return False


def is_compiled_with_cinn():
    return False


def is_compiled_with_tpu():
    return True


def is_compiled_with_ipu():
    return False


def is_compiled_with_mlu():
    return False


def synchronize(device=None):
    """Block until all queued device work is complete."""
    (jax.device_put(0) + 0).block_until_ready()


def _memory_stats(device=None):
    """jax.Device.memory_stats() for the selected device, {} when the
    backend exposes no allocator stats (CPU). Resolves "kind:idx"
    strings and plain int device ids (the common Paddle convention)
    WITHOUT touching the set_device global."""
    if isinstance(device, (str, int)):
        devs = jax.devices()
        if isinstance(device, int):
            idx = device
        else:
            idx = int(device.split(":")[1]) if ":" in device else 0
        d = devs[idx % len(devs)]
    elif device is not None:
        d = device
    else:
        d = _current or _default_device()
    if hasattr(d, "memory_stats"):
        try:
            return d.memory_stats() or {}
        except Exception:
            return {}
    return {}


def max_memory_allocated(device=None):
    """Peak bytes of device memory held by live buffers since process
    start (parity: paddle.device.cuda.max_memory_allocated). Backed by
    jax.Device.memory_stats()['peak_bytes_in_use'] — on TPU this is the
    HBM high-water mark, the number that proves a donated train step is
    NOT holding a second full copy of the model. The CPU backend exposes
    no allocator stats, so the process peak RSS stands in (keeps the API
    returning sane nonzero values everywhere). Each query lands in the
    telemetry store (a "device.memory" span + the device.peak_bytes
    gauge), so Profiler.summary() carries the memory high-water mark."""
    import time
    from ..profiler import statistic as _stat
    from ..profiler import monitor as _monitor
    t0 = time.perf_counter()
    peak = _memory_stats(device).get("peak_bytes_in_use", 0)
    if not peak:
        import resource
        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    _stat.record_span("device.memory", time.perf_counter() - t0)
    _monitor.gauge("device.peak_bytes").set(int(peak))
    return int(peak)


def memory_allocated(device=None):
    """Bytes of device memory currently held by live buffers."""
    cur = int(_memory_stats(device).get("bytes_in_use", 0))
    from ..profiler import monitor as _monitor
    _monitor.gauge("device.bytes_in_use").set(cur)
    return cur


def max_memory_reserved(device=None):
    """Peak bytes the allocator reserved from the device (>= allocated)."""
    stats = _memory_stats(device)
    return int(stats.get("peak_bytes_reserved",
                         stats.get("peak_bytes_in_use", 0)))


def memory_reserved(device=None):
    """Bytes the allocator currently reserves from the device."""
    stats = _memory_stats(device)
    return int(stats.get("bytes_reserved", stats.get("bytes_in_use", 0)))


def get_device_properties(device=None):
    d = _current or _default_device()
    stats = _memory_stats(device)

    class _Props:
        name = str(d)
        major, minor = 0, 0
        total_memory = stats.get("bytes_limit", 0)
        multi_processor_count = 1
    return _Props()


class Stream:
    """XLA orders execution itself; streams are a no-op compatibility shim."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        self._t = None

    def record(self, stream=None):
        # dispatch is async; sync so the timestamp marks completed work
        synchronize()
        import time
        self._t = time.perf_counter()

    def query(self):
        return True

    def synchronize(self):
        synchronize()

    def elapsed_time(self, end_event):
        """Milliseconds between two recorded events (CUDA Event parity)."""
        if self._t is None or end_event._t is None:
            raise RuntimeError("elapsed_time() on un-recorded events")
        return max((end_event._t - self._t) * 1000.0, 0.0)


from . import cuda  # noqa: E402  (real submodule, paddle parity)


# ------------------------------------------------- extra device-type API
# Parity: python/paddle/device/__init__.py (XPU/IPU/MLU places exist as
# types so user code can isinstance-check; all map onto the single TPU
# place — there is no per-op placement under XLA).

def get_cudnn_version():
    return None


class _AltPlace:
    def __init__(self, dev_id=0):
        self.dev_id = dev_id

    def __repr__(self):
        return f"{type(self).__name__}({self.dev_id})"

    def get_device_id(self):
        return self.dev_id


class XPUPlace(_AltPlace):
    pass


class IPUPlace(_AltPlace):
    def __init__(self):
        super().__init__(0)


class MLUPlace(_AltPlace):
    pass


def get_all_device_type():
    return sorted({("tpu" if d.platform in ("tpu", "axon") else d.platform)
                   for d in jax.devices()})


def get_all_custom_device_type():
    return []


def get_available_device():
    return [f"{('tpu' if d.platform in ('tpu', 'axon') else d.platform)}"
            f":{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return []


__all__ += ["get_cudnn_version", "XPUPlace", "IPUPlace", "MLUPlace",
            "get_all_device_type", "get_all_custom_device_type",
            "get_available_device", "get_available_custom_device",
            "is_compiled_with_cinn", "is_compiled_with_ipu",
            "is_compiled_with_mlu"]
