"""ZeRO sharding stages. Parity:
python/paddle/distributed/fleet/meta_parallel/sharding/ (sharding_stage2/
sharding_stage3 + sharding_optimizer_stage2).

Reference mechanics: each rank owns a slice of optimizer state (stage 1/2)
or parameters (stage 3) and materializes the rest on demand with NCCL
broadcast/allgather. TPU-native: the state/param pytrees simply carry a
NamedSharding with the 'sharding' mesh axis; XLA's SPMD partitioner emits
the reduce-scatter for gradient averaging and the all-gather before use —
the exact ZeRO communication schedule — without bespoke runtime classes.
These wrappers select real behavior: the `_sharding_stage` marker they set
is consumed by fleet.build_train_step, which passes it to
HybridTrainStep(sharding_stage=...) — stage 2 pins gradients to the
'sharding' axis (update on grad shards; sync lowers to reduce-scatter on
TPU), stage 3 stores the parameters themselves sharded (all-gather at use
sites). See tests/test_distributed.py::TestZeROStages.
"""
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ....framework.core import Tensor

__all__ = ["ShardingOptimizerStage2", "ShardingStage2", "ShardingStage3",
           "GroupShardedOptimizerStage2", "GroupShardedStage2",
           "GroupShardedStage3"]


class ShardingOptimizerStage2:
    """Optimizer-state (+grad) sharding over the 'sharding' axis."""

    def __init__(self, params, optim, group=None, offload=False, **kw):
        self._optim = optim
        self._params = params
        optim._sharding_stage = 2

    def __getattr__(self, name):
        return getattr(self._optim, name)

    def step(self):
        self._optim.step()

    def clear_grad(self):
        self._optim.clear_grad()


class ShardingStage2:
    """Layer wrapper marking grads for reduce-scatter over 'sharding'."""

    def __init__(self, layer, sharding_optimizer=None, group=None,
                 sync_buffers=False, buffer_max_size=2 ** 23, **kw):
        self._layer = layer
        layer._sharding_stage = 2

    def __call__(self, *args, **kwargs):
        return self._layer(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._layer, name)


class ShardingStage3:
    """Parameter sharding (ZeRO-3): params live sharded over 'sharding'
    and are all-gathered per-layer by XLA at use sites."""

    def __init__(self, layer, device="tpu", group=None, sync_buffers=False,
                 segment_size=2 ** 20, **kw):
        self._layer = layer
        layer._sharding_stage = 3

    def __call__(self, *args, **kwargs):
        return self._layer(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._layer, name)

    def get_all_parameters(self):
        return self._layer.parameters()


GroupShardedOptimizerStage2 = ShardingOptimizerStage2
GroupShardedStage2 = ShardingStage2
GroupShardedStage3 = ShardingStage3
