"""paddle.callbacks — flat alias of the hapi callback classes.

Parity: /root/reference/python/paddle/callbacks.py (pure re-export).
"""
from .hapi.callbacks import (Callback, ProgBarLogger, ModelCheckpoint,
                             VisualDL, LRScheduler, EarlyStopping,
                             ReduceLROnPlateau)

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "VisualDL",
           "LRScheduler", "EarlyStopping", "ReduceLROnPlateau"]
