"""Weight decay regularizers. Parity: python/paddle/regularizer.py."""
import jax.numpy as jnp

__all__ = ["L1Decay", "L2Decay"]


class WeightDecayRegularizer:
    def __init__(self, coeff=0.0):
        self._coeff = coeff

    def grad_term(self, param_value):
        """Extra gradient contribution dR/dw."""
        raise NotImplementedError


class L1Decay(WeightDecayRegularizer):
    def grad_term(self, param_value):
        return self._coeff * jnp.sign(param_value)


class L2Decay(WeightDecayRegularizer):
    def grad_term(self, param_value):
        return self._coeff * param_value
