"""paddle.linalg namespace. Parity: python/paddle/linalg.py."""
from .tensor.linalg import (matmul, dot, bmm, mv, mm, addmm, cross, norm,
                            dist, cond, cholesky, cholesky_solve, qr, svd,
                            eig, eigh, eigvals, eigvalsh, inv, pinv, solve,
                            triangular_solve, lstsq, matrix_power,
                            matrix_rank, det, slogdet, multi_dot, lu,
                            lu_unpack, corrcoef, cov)
