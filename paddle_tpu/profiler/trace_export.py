"""Unified Chrome-trace-event export: one JSON file, openable in
Perfetto (ui.perfetto.dev) or chrome://tracing, carrying everything the
process recorded — host spans as per-thread duration tracks, metric
updates as counter tracks, train-step / serving-batch records as
synthetic tracks, and structured anomalies as instant markers.

Parity: the reference profiler's `export_chrome_tracing` — but where
the reference serializes its C++ HostTraceLevel events, this renders
the flight-recorder ring (`flight_recorder.py`), which every hot path
already feeds. The export is therefore available at ANY moment of a
live process (it is a snapshot of the recent tail, ring-bounded), not
only inside a Profiler start/stop window.

Track layout (what you see in Perfetto):

- pid = the process rank (launch env), process name "paddle_tpu rank N"
  — `tools/merge_traces.py` merges per-rank files into one timeline;
- one thread track per real host thread (named: MainThread,
  serve-dispatch, prefetch producer, ...), duration events from spans;
- synthetic tracks "train steps" / "serve batches" rendering the
  exported step/serve records with their metadata as args, and a
  "checkpoint" track with one slice per save/restore/GC (phase
  sub-slices: snapshot → serialize → write → commit) from the
  `kind:"ckpt"` records, so async-save overlap with training is
  visible next to the step slices;
- "serving requests" lanes: one slice per request LIFETIME (submit ->
  terminal, labelled engine/id/outcome, the full `kind:"request"`
  record in its args) with queued/prefill/decode phase sub-slices
  nested inside; concurrent requests spread over a small fixed set of
  lanes so overlapping lifetimes stay readable;
- a "routing" track with one instant slice per `kind:"route"` decision
  (dispatch / reject / handoff, the record in its args); handoff
  decisions additionally draw s/f flow arrows from the prefill
  request's lane to the decode request's lane, joined on request_id —
  the disaggregated handoff rendered as the arrow it is;
- a counter track per metric (queue depth, prefetch depth, device
  memory, host.blocked_s, ...) plus `kv.<engine>.*` page-pool tracks
  from `kind:"kvcache"` snapshots, `fleet.<router>.*` tracks from
  `kind:"fleet"` snapshots, and `mem.<tag>` per-tag byte tracks from
  `kind:"memory"` attribution records;
- instant markers for `kind:"event"` anomalies (NaN, loss spike,
  watchdog, ...).

Timestamps are unix-epoch microseconds (spans carry a perf_counter →
wall anchor), so traces from different ranks on one host line up.
"""
import json
import math
import os
import threading

from . import flight_recorder
from . import monitor

__all__ = ["chrome_trace_events", "write_chrome_trace",
           "TRAIN_TID", "SERVE_TID", "EVENT_TID", "COMPILE_TID",
           "REQUEST_TID", "REQUEST_LANES", "CKPT_TID", "COLLECTIVE_TID",
           "ROUTE_TID"]

# synthetic track ids for record-derived events; real thread idents are
# pointer-sized on linux, so small ints can never collide with them
TRAIN_TID = 1
SERVE_TID = 2
EVENT_TID = 3
COMPILE_TID = 4
REQUEST_TID = 5     # first "serving requests" lane
REQUEST_LANES = 12  # concurrent-request lanes before reuse
CKPT_TID = 20       # "checkpoint" track (after the request lanes)
COLLECTIVE_TID = 21  # "collectives" track (sampled kind:"collective"
                     # records — the cross-rank lane a merged,
                     # clock-aligned timeline lines up across pids)
ROUTE_TID = 22       # "routing" track (kind:"route" decision slices;
                     # handoff decisions additionally draw s/f flow
                     # arrows from the prefill request lane to the
                     # decode request lane, joined on request_id)


def _sanitize(obj):
    """JSON-strict copy: non-finite floats become strings (Perfetto's
    JSON parser rejects bare NaN/Infinity tokens)."""
    if isinstance(obj, float) and not math.isfinite(obj):
        return repr(obj)
    if isinstance(obj, dict):
        return {k: _sanitize(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_sanitize(v) for v in obj]
    return obj


def _thread_names(tids):
    """ident -> human name for the threads still alive at export time."""
    alive = {t.ident: t.name for t in threading.enumerate()}
    return {tid: alive.get(tid, f"thread-{tid}") for tid in tids}


def chrome_trace_events(snap=None, rank=None):
    """The flight-recorder snapshot as a list of Chrome trace events
    (dicts), sorted by timestamp — ready to wrap in {"traceEvents": …}."""
    if snap is None:
        snap = flight_recorder.snapshot()
    if rank is None:
        rank = monitor.rank()
    pid = int(rank)
    meta = [
        {"ph": "M", "name": "process_name", "pid": pid, "tid": 0, "ts": 0,
         "args": {"name": f"paddle_tpu rank {rank}"}},
        {"ph": "M", "name": "process_sort_index", "pid": pid, "tid": 0,
         "ts": 0, "args": {"sort_index": int(rank)}},
        {"ph": "M", "name": "thread_name", "pid": pid, "tid": TRAIN_TID,
         "ts": 0, "args": {"name": "train steps"}},
        {"ph": "M", "name": "thread_name", "pid": pid, "tid": SERVE_TID,
         "ts": 0, "args": {"name": "serve batches"}},
        {"ph": "M", "name": "thread_name", "pid": pid, "tid": EVENT_TID,
         "ts": 0, "args": {"name": "events"}},
        {"ph": "M", "name": "thread_name", "pid": pid, "tid": COMPILE_TID,
         "ts": 0, "args": {"name": "compilation"}},
        {"ph": "M", "name": "thread_name", "pid": pid, "tid": CKPT_TID,
         "ts": 0, "args": {"name": "checkpoint"}},
        {"ph": "M", "name": "thread_name", "pid": pid,
         "tid": COLLECTIVE_TID, "ts": 0,
         "args": {"name": "collectives"}},
        {"ph": "M", "name": "thread_name", "pid": pid,
         "tid": ROUTE_TID, "ts": 0, "args": {"name": "routing"}},
    ]
    events = []

    # host spans -> per-thread duration ("X" complete) events; Perfetto
    # reconstructs nesting from ts/dur containment, which the recorder's
    # child-closes-before-parent ordering guarantees
    for s in snap.get("spans", ()):
        events.append({
            "name": s["name"], "ph": "X", "cat": "host_span",
            "ts": s["ts"] * 1e6, "dur": max(s["dur_s"], 0.0) * 1e6,
            "pid": pid, "tid": s["tid"],
            "args": {"depth": s.get("depth", 0)}})

    # metric updates -> counter tracks (one per metric name)
    for m in snap.get("samples", ()):
        events.append({
            "name": m["name"], "ph": "C", "cat": "metric",
            "ts": m["ts"] * 1e6, "pid": pid, "tid": 0,
            "args": {"value": _sanitize(m["value"])}})

    # exported records -> synthetic tracks; the record itself rides in
    # args so a slice click shows step/compile/mfu or batch/pad/latency
    request_recs = []  # (start_s, latency_s, record): laned below
    handoff_routes = []  # handoff route records: flow arrows below
    for rec in snap.get("records", ()):
        kind = rec.get("kind")
        ts = float(rec.get("ts", 0.0))
        if kind == "step":
            dur = max(float(rec.get("step_time_s", 0.0)), 0.0)
            events.append({
                "name": f"step {rec.get('step', '?')}", "ph": "X",
                "cat": "train", "ts": (ts - dur) * 1e6, "dur": dur * 1e6,
                "pid": pid, "tid": TRAIN_TID, "args": _sanitize(rec)})
        elif kind == "scan":
            dur = max(float(rec.get("dispatch_s", 0.0)), 0.0)
            events.append({
                "name": f"run_steps x{rec.get('steps', '?')}", "ph": "X",
                "cat": "train", "ts": (ts - dur) * 1e6, "dur": dur * 1e6,
                "pid": pid, "tid": TRAIN_TID, "args": _sanitize(rec)})
        elif kind == "serve":
            dur = max(float(rec.get("latency_s", 0.0)), 0.0)
            events.append({
                "name": f"{rec.get('engine', 'serve')} "
                        f"batch={rec.get('batch_size', '?')}",
                "ph": "X", "cat": "serve", "ts": (ts - dur) * 1e6,
                "dur": dur * 1e6, "pid": pid, "tid": SERVE_TID,
                "args": _sanitize(rec)})
        elif kind == "compile":
            # the compilation observatory's ledger records: one slice
            # per lower and one per XLA compile on the named
            # "compilation" track, so Perfetto shows where compile time
            # went right next to the train steps it delayed. The record
            # stamp lands just after the compile returns, so the slices
            # are reconstructed backwards from it.
            lower = max(float(rec.get("lower_s", 0.0)), 0.0)
            comp = max(float(rec.get("compile_s", 0.0)), 0.0)
            tag = rec.get("tag", "?")
            events.append({
                "name": f"lower {tag}", "ph": "X", "cat": "compile",
                "ts": (ts - comp - lower) * 1e6, "dur": lower * 1e6,
                "pid": pid, "tid": COMPILE_TID, "args": _sanitize(rec)})
            events.append({
                "name": f"compile {tag}", "ph": "X", "cat": "compile",
                "ts": (ts - comp) * 1e6, "dur": comp * 1e6,
                "pid": pid, "tid": COMPILE_TID, "args": _sanitize(rec)})
        elif kind == "request":
            # one slice per request LIFETIME, reconstructed backwards
            # from the terminal record's stamp; laned after the loop so
            # overlapping lifetimes don't render as bogus nesting
            lat = rec.get("latency_s", 0.0)
            if isinstance(lat, (int, float)) and not isinstance(lat, bool):
                lat = max(float(lat), 0.0)
                request_recs.append((ts - lat, lat, rec))
        elif kind == "route":
            # the routing track: one zero-duration slice per decision
            # (the decision is an instant — its CONSEQUENCE is the
            # request slice it points at), full record in args
            outcome = rec.get("outcome", "?")
            if outcome == "handoff":
                name = (f"handoff {rec.get('from_engine', '?')}"
                        f"→{rec.get('engine', '?')}")
                handoff_routes.append(rec)
            elif outcome == "rejected":
                name = f"reject [{rec.get('slo_class', '?')}]"
            else:
                name = (f"dispatch {rec.get('engine', '?')} "
                        f"[{rec.get('slo_class', '?')}]")
            events.append({
                "name": name, "ph": "X", "cat": "route",
                "ts": ts * 1e6, "dur": 0.0, "pid": pid,
                "tid": ROUTE_TID, "args": _sanitize(rec)})
        elif kind == "fleet":
            # fleet snapshots -> router-level counter tracks next to
            # the per-engine kv.* series
            router = rec.get("router", "router")
            for key in ("queue_depth", "active", "admittable_pages",
                        "outstanding_claims"):
                v = rec.get(key)
                if isinstance(v, (int, float)) and \
                        not isinstance(v, bool):
                    events.append({
                        "name": f"fleet.{router}.{key}", "ph": "C",
                        "cat": "fleet", "ts": ts * 1e6, "pid": pid,
                        "tid": 0, "args": {"value": _sanitize(v)}})
        elif kind == "kvcache":
            # page-pool counter tracks, per engine (two engines' pools
            # must not interleave into one series)
            eng = rec.get("engine", "serve")
            for key in ("free_pages", "held_pages", "shared_pages",
                        "registered_pages", "evictable_pages"):
                v = rec.get(key)
                if isinstance(v, (int, float)) and \
                        not isinstance(v, bool):
                    events.append({
                        "name": f"kv.{eng}.{key}", "ph": "C",
                        "cat": "kvcache", "ts": ts * 1e6, "pid": pid,
                        "tid": 0, "args": {"value": _sanitize(v)}})
        elif kind == "memory":
            # per-tag memory counter tracks (mem.params, mem.kv_pool.*,
            # ...): each attribution tag becomes its own byte series,
            # plus the attributed/unattributed split — the Perfetto
            # view of WHO holds HBM over time
            tags = rec.get("tags")
            if isinstance(tags, dict):
                for tag, v in tags.items():
                    if isinstance(v, (int, float)) and \
                            not isinstance(v, bool):
                        events.append({
                            "name": f"mem.{tag}", "ph": "C",
                            "cat": "memory", "ts": ts * 1e6, "pid": pid,
                            "tid": 0, "args": {"value": _sanitize(v)}})
            for key in ("attributed_bytes", "unattributed_bytes",
                        "device_bytes_in_use", "fragmentation"):
                v = rec.get(key)
                if isinstance(v, (int, float)) and \
                        not isinstance(v, bool):
                    events.append({
                        "name": f"mem.{key}", "ph": "C",
                        "cat": "memory", "ts": ts * 1e6, "pid": pid,
                        "tid": 0, "args": {"value": _sanitize(v)}})
        elif kind == "ckpt":
            # the checkpoint track: one slice per save (reconstructed
            # backwards from the commit-time stamp) with the
            # snapshot/serialize/write/commit phases as sub-slices, one
            # per restore/gc — next to the train steps they overlap
            # with, which is the visual proof the async writer is off
            # the critical path (docs/FAULT_TOLERANCE.md)
            op = rec.get("op", "?")
            dur = rec.get("total_s", 0.0)
            dur = max(float(dur), 0.0) if isinstance(dur, (int, float)) \
                and not isinstance(dur, bool) else 0.0
            name = f"ckpt {op} step {rec.get('step', '?')}"
            if op == "restore" and not rec.get("verified", True):
                name += " [no valid checkpoint]"
            if op == "save" and not rec.get("committed", True):
                name += " [FAILED]"
            events.append({
                "name": name, "ph": "X", "cat": "ckpt",
                "ts": (ts - dur) * 1e6, "dur": dur * 1e6,
                "pid": pid, "tid": CKPT_TID, "args": _sanitize(rec)})
            if op == "save":
                t = ts - dur
                for phase in ("snapshot", "serialize", "write",
                              "commit"):
                    d = rec.get(phase + "_s")
                    if isinstance(d, (int, float)) and \
                            not isinstance(d, bool) and d > 0:
                        events.append({
                            "name": phase, "ph": "X", "cat": "ckpt",
                            "ts": t * 1e6, "dur": float(d) * 1e6,
                            "pid": pid, "tid": CKPT_TID, "args": {}})
                        t += d
        elif kind == "collective":
            # sampled per-collective slices (the distributed
            # observatory): one X-slice per record on the "collectives"
            # track, reconstructed backwards from the post-call stamp.
            # After merge_traces' clock alignment these lanes line up
            # across rank pids — the cross-rank overlap evidence.
            dur = rec.get("wall_s", 0.0)
            dur = max(float(dur), 0.0) if isinstance(dur, (int, float)) \
                and not isinstance(dur, bool) else 0.0
            name = f"{rec.get('op', '?')}@{rec.get('group', '?')}"
            if rec.get("traced"):
                name += " [traced]"
            events.append({
                "name": name, "ph": "X", "cat": "collective",
                "ts": (ts - dur) * 1e6, "dur": dur * 1e6,
                "pid": pid, "tid": COLLECTIVE_TID,
                "args": _sanitize(rec)})
        elif kind == "rankstat":
            # per-rank skew telemetry as counter tracks: step-time
            # p50/p99 + collective-wait share next to the step slices
            for key in ("step_time_p50_s", "step_time_p99_s",
                        "collective_wait_share", "host_blocked_s"):
                v = rec.get(key)
                if isinstance(v, (int, float)) and \
                        not isinstance(v, bool):
                    events.append({
                        "name": f"rankstat.{key}", "ph": "C",
                        "cat": "rankstat", "ts": ts * 1e6, "pid": pid,
                        "tid": 0, "args": {"value": _sanitize(v)}})
        elif kind == "health":
            for key in ("grad_norm", "param_norm", "update_ratio",
                        "loss"):
                v = rec.get(key)
                if isinstance(v, (int, float)):
                    events.append({
                        "name": f"health.{key}", "ph": "C",
                        "cat": "health", "ts": ts * 1e6, "pid": pid,
                        "tid": 0, "args": {"value": _sanitize(v)}})

    # "serving requests" lanes: greedy interval partitioning — a
    # request takes the first lane free at its start, so concurrent
    # lifetimes land on different tids and phase sub-slices (queued ->
    # prefill -> decode) nest INSIDE their own request only
    lane_busy_until = []
    used_lanes = set()
    req_slice = {}  # (engine, request_id) -> (tid, start_s, end_s)
    for start, lat, rec in sorted(request_recs, key=lambda r: r[0]):
        lane = next((i for i, end in enumerate(lane_busy_until)
                     if start >= end), None)
        if lane is None:
            if len(lane_busy_until) < REQUEST_LANES:
                lane = len(lane_busy_until)
                lane_busy_until.append(0.0)
            else:  # saturated: least-recently-busy lane (readability
                # degrades gracefully, nothing is dropped)
                lane = min(range(len(lane_busy_until)),
                           key=lambda i: lane_busy_until[i])
        # max(): a short request reusing a saturated lane must not
        # rewind its busy-until past a longer resident slice, or later
        # requests would stack on top of it
        lane_busy_until[lane] = max(lane_busy_until[lane], start + lat)
        tid = REQUEST_TID + lane
        used_lanes.add(lane)
        rid = rec.get("request_id")
        if isinstance(rid, str) and rid:
            req_slice[(rec.get("engine"), rid)] = (tid, start,
                                                   start + lat)
        name = (f"{rec.get('engine', 'serve')} "
                f"{rec.get('request_id', '?')} "
                f"[{rec.get('outcome', '?')}]")
        events.append({
            "name": name, "ph": "X", "cat": "request",
            "ts": start * 1e6, "dur": lat * 1e6,
            "pid": pid, "tid": tid, "args": _sanitize(rec)})
        t = start
        for phase, key in (("queued", "queue_s"),
                           ("prefill", "prefill_s"),
                           ("decode", "decode_s")):
            d = rec.get(key)
            if isinstance(d, (int, float)) and not isinstance(d, bool) \
                    and d > 0:
                events.append({
                    "name": phase, "ph": "X", "cat": "request",
                    "ts": t * 1e6, "dur": max(float(d), 0.0) * 1e6,
                    "pid": pid, "tid": tid, "args": {}})
                t += d
    for lane in sorted(used_lanes):
        meta.append({
            "ph": "M", "name": "thread_name", "pid": pid,
            "tid": REQUEST_TID + lane, "ts": 0,
            "args": {"name": "serving requests" if lane == 0
                     else f"serving requests ({lane})"}})
    # handoff flow arrows: prefill request lane -> decode request lane,
    # joined on (engine, request_id). The start anchors at the prefill
    # slice's END (where its trace closed with outcome "handoff"), the
    # finish at the route decision's stamp inside the decode slice
    # (clamped forward — an arrow must not point into the past). Arrows
    # emit only as s/f PAIRS (both slices present), which is exactly
    # what the trace lint enforces.
    for i, rec in enumerate(handoff_routes):
        rid = rec.get("request_id")
        if not isinstance(rid, str) or not rid:
            continue
        pre = req_slice.get((rec.get("from_engine"), rid))
        dec = req_slice.get((rec.get("engine"), rid))
        if pre is None or dec is None:
            continue
        t_start = pre[2]
        t_finish = max(float(rec.get("ts", t_start)), t_start)
        fid = f"handoff:{rid}:{i}"
        flow = {"name": "handoff", "cat": "handoff", "id": fid,
                "pid": pid}
        events.append(dict(flow, ph="s", ts=t_start * 1e6,
                           tid=pre[0]))
        events.append(dict(flow, ph="f", bp="e", ts=t_finish * 1e6,
                           tid=dec[0]))
    # structured anomalies: the events ring is their ONE home —
    # record_event rings them here and exports the JSONL line itself
    # (monitor.export_step _ring=False), so the records ring never
    # duplicates them
    for ev in snap.get("events", ()):
        events.append({
            "name": ev.get("event", "event"), "ph": "i", "s": "p",
            "cat": "event", "ts": float(ev.get("ts", 0.0)) * 1e6,
            "pid": pid, "tid": EVENT_TID, "args": _sanitize(ev)})

    # name real thread tracks (after the span loop knows the tids)
    tids = sorted({e["tid"] for e in events if e.get("cat") == "host_span"})
    for tid, name in _thread_names(tids).items():
        meta.append({"ph": "M", "name": "thread_name", "pid": pid,
                     "tid": tid, "ts": 0, "args": {"name": name}})

    events.sort(key=lambda e: e["ts"])  # sorted ts per track, globally
    return meta + events


def write_chrome_trace(path, snap=None, rank=None, extra=None):
    """Write the trace JSON to `path` and return it. Chrome trace JSON
    object format: {"traceEvents": [...], "displayTimeUnit": "ms"}.
    `otherData.clock_offset_s` carries this rank's estimated wall-clock
    offset vs rank 0 (the coordinator handshake at init_parallel_env —
    profiler/dist_observatory.py); `tools/merge_traces.py` subtracts it
    per input file so a merged multi-rank timeline is clock-aligned."""
    from . import dist_observatory
    payload = {"traceEvents": chrome_trace_events(snap=snap, rank=rank),
               "displayTimeUnit": "ms",
               "otherData": dict(extra or {},
                                 exporter="paddle_tpu.profiler",
                                 clock_offset_s=
                                 dist_observatory.clock_offset_s(),
                                 rank=monitor.rank()
                                 if rank is None else rank)}
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w") as f:
        # default=str: a ringed record can carry values export_step's
        # own json.dumps would have rejected (the ring append runs
        # first) — a stringified arg beats a crashed export
        json.dump(payload, f, default=str)
    return path
