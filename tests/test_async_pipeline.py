"""Async step pipeline (ISSUE 3): device prefetch ring, deferred loss
handles, scanned gradient accumulation, and the no-hot-sync fence.

Proof points:
- TrainStep returns a DeferredLoss (still a Tensor); resolution is lazy,
  cached, and recorded in host.blocked_s.
- The prefetch ring preserves order, places leaves on device (with a
  HybridTrainStep's mesh shardings when given), surfaces producer
  exceptions, and survives early abandonment.
- accumulate(k) numerics match ONE k-times-larger-batch step with
  exactly one optimizer update, standalone and through
  fit(accumulate_grad_batches=k).
- Overlap: a fit loop over a dataset with artificial per-batch host
  latency runs >= 1.3x faster with the ring + deferred losses than the
  synchronous (resolve-every-step, no ring) path, and the steady-state
  `dataloader.next` span stays flat.
- tools/check_no_hot_sync.py passes on the repo and catches a planted
  violation.
"""
import importlib.util
import os
import time

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import optimizer as opt
from paddle_tpu.io import DataLoader, Dataset, TensorDataset
from paddle_tpu.io.device_prefetch import (DevicePrefetchRing,
                                           device_prefetch_iterator)
from paddle_tpu.jit import TrainStep, DeferredLoss
from paddle_tpu.profiler import monitor, statistic

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    statistic.reset_statistics()
    monitor.reset_metrics()
    yield


def _mk_step(seed=0, width=16):
    paddle.seed(seed)
    m = nn.Sequential(nn.Linear(8, width), nn.Tanh(), nn.Linear(width, 4))
    o = opt.AdamW(learning_rate=1e-2, parameters=m.parameters())
    return TrainStep(m, lambda a, b: nn.functional.mse_loss(a, b), o)


def _xy(n=32, seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randn(n, 8).astype(np.float32),
            rng.randn(n, 4).astype(np.float32))


# -- deferred loss -----------------------------------------------------

def test_deferred_loss_is_lazy_cached_and_recorded():
    step = _mk_step()
    x, y = _xy()
    loss = step(paddle.to_tensor(x), paddle.to_tensor(y))
    assert isinstance(loss, DeferredLoss)
    assert isinstance(loss, paddle.Tensor)  # drop-in for old call sites
    assert loss._resolved is None  # nothing resolved until read
    blocked = monitor.get_metric("host.blocked_s")
    assert blocked is None or blocked.count == 0
    v1 = float(loss)
    assert monitor.get_metric("host.blocked_s").count == 1
    v2 = float(loss.item())
    assert v1 == v2  # cached: second read doesn't touch the device
    assert monitor.get_metric("host.blocked_s").count == 1
    assert np.isfinite(v1)
    assert monitor.host_blocked_s() >= 0.0


def test_train_batch_and_eval_batch_keep_float_contract():
    x, y = _xy()
    ds = TensorDataset([paddle.to_tensor(x), paddle.to_tensor(y)])
    m = paddle.Model(nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                                   nn.Linear(16, 4)))
    m.prepare(opt.AdamW(learning_rate=1e-2,
                        parameters=m.network.parameters()),
              lambda a, b: nn.functional.mse_loss(a, b))
    losses = m.train_batch([paddle.to_tensor(x)], paddle.to_tensor(y))
    assert isinstance(losses[0], float)
    l, _ = m.eval_batch([paddle.to_tensor(x)], paddle.to_tensor(y))
    assert isinstance(l[0], float)
    # the async variant evaluate() uses returns unresolved handles
    h, _ = m._eval_batch_async([paddle.to_tensor(x)], paddle.to_tensor(y))
    assert isinstance(h[0], DeferredLoss) and h[0]._resolved is None
    res = m.evaluate(ds, batch_size=8, verbose=0)
    assert np.isfinite(res["loss"][0])


# -- prefetch ring -----------------------------------------------------

def test_ring_preserves_order_and_places_on_device():
    batches = [[paddle.to_tensor(np.full((4, 8), i, np.float32)),
                paddle.to_tensor(np.full((4,), i, np.int64))]
               for i in range(10)]
    out = list(device_prefetch_iterator(iter(batches), depth=3))
    assert len(out) == 10
    for i, b in enumerate(out):
        assert isinstance(b[0], paddle.Tensor)
        assert isinstance(b[0].value, jax.Array)  # device-resident
        np.testing.assert_array_equal(b[0].numpy(),
                                      np.full((4, 8), i, np.float32))
    assert statistic.get_events("prefetch.h2d")[0]["count"] == 10


def test_ring_h2d_bytes_counts_real_traffic_only():
    # already-resident jax-backed batches pass through free...
    resident = [[paddle.to_tensor(np.zeros((4, 8), np.float32))]]
    list(device_prefetch_iterator(iter(resident), depth=2))
    m = monitor.get_metric("prefetch.h2d_bytes")
    assert m is None or m.value == 0
    # ...host (numpy) leaves are real H2D and are counted exactly
    host = [[np.zeros((4, 8), np.float32)]]
    out = list(device_prefetch_iterator(iter(host), depth=2))
    assert isinstance(out[0][0].value, jax.Array)
    assert monitor.get_metric("prefetch.h2d_bytes").value == 4 * 8 * 4


def test_ring_propagates_producer_exception():
    def source():
        yield [paddle.to_tensor(np.zeros((2, 2), np.float32))]
        raise RuntimeError("boom in the dataset")

    it = device_prefetch_iterator(source(), depth=2)
    next(it)
    with pytest.raises(RuntimeError, match="boom in the dataset"):
        next(it)


def test_ring_survives_early_abandonment():
    def source():
        for i in range(10_000):
            yield [paddle.to_tensor(np.zeros((2, 2), np.float32))]

    ring = DevicePrefetchRing(source(), depth=2)
    for _, batch in zip(range(3), ring):
        pass
    ring.close()
    ring._thread.join(timeout=5)
    assert not ring._thread.is_alive()


def test_ring_places_with_hybrid_mesh_shardings():
    from paddle_tpu.distributed.env import build_mesh
    from paddle_tpu.distributed.fleet.hybrid_train import HybridTrainStep

    paddle.seed(0)
    m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    o = opt.AdamW(learning_rate=1e-3, parameters=m.parameters())
    mesh = build_mesh(dp=8)
    step = HybridTrainStep(
        m, lambda a, b: nn.functional.mse_loss(a, b), o, mesh)
    x, y = _xy(16)
    batches = [[paddle.to_tensor(x), paddle.to_tensor(y)]
               for _ in range(3)]
    loss = None
    for b in device_prefetch_iterator(iter(batches), depth=2,
                                      sharding_fn=step.input_sharding):
        # staged with the step's input shardings: _prep passes through
        assert b[0].value.sharding == step.input_sharding(b[0].value)
        loss = step(*b)
    assert isinstance(loss, DeferredLoss)
    assert np.isfinite(float(loss))


def test_dataloader_prefetch_to_device_knob():
    assert DataLoader([1], prefetch_to_device=True).prefetch_to_device == 2
    assert DataLoader([1]).prefetch_to_device == 0
    x, y = _xy(16)
    ds = TensorDataset([paddle.to_tensor(x), paddle.to_tensor(y)])
    loader = DataLoader(ds, batch_size=4, prefetch_to_device=2)
    seen = [b for b in loader]
    assert len(seen) == 4
    assert isinstance(seen[0][0].value, jax.Array)


# -- scanned gradient accumulation -------------------------------------

def test_accumulate_matches_one_kx_batch_step():
    x, y = _xy(32)
    step_a = _mk_step()
    loss_a = step_a(paddle.to_tensor(x), paddle.to_tensor(y))

    step_b = _mk_step()
    xs = paddle.to_tensor(x.reshape(4, 8, 8))
    ys = paddle.to_tensor(y.reshape(4, 8, 4))
    loss_b = step_b.accumulate(4, xs, ys)

    np.testing.assert_allclose(float(loss_a), float(loss_b),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(step_a.params["0.weight"]),
                               np.asarray(step_b.params["0.weight"]),
                               rtol=1e-5, atol=1e-6)
    # exactly ONE optimizer update for the k microbatches
    assert step_b._step_i == 1
    # and the leading-dim contract is enforced
    with pytest.raises(ValueError, match="leading microbatch dim"):
        step_b.accumulate(3, xs, ys)


def test_fit_accumulate_handles_ragged_tail_batch():
    # 14 samples, batch 4, drop_last=False -> batches of 4,4,4,2: the
    # ragged tail must flush the pending group instead of jnp.stack-ing
    # mismatched shapes
    x, y = _xy(14)
    ds = TensorDataset([paddle.to_tensor(x), paddle.to_tensor(y)])
    paddle.seed(0)
    m = paddle.Model(nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                                   nn.Linear(16, 4)))
    m.prepare(opt.AdamW(learning_rate=1e-2,
                        parameters=m.network.parameters()),
              lambda a, b: nn.functional.mse_loss(a, b))
    m.fit(ds, batch_size=4, epochs=1, shuffle=False, verbose=0,
          accumulate_grad_batches=2)
    # groups: [4,4] stacked + [4] flushed before the ragged [2] = 3 ups
    assert m._train_step._step_i == 3


def test_deferred_loss_supports_format_strings():
    step = _mk_step()
    x, y = _xy()
    loss = step(paddle.to_tensor(x), paddle.to_tensor(y))
    # pre-deferred callbacks format the loss directly — must resolve,
    # not crash on Tensor.__format__
    assert f"{loss:.4f}" == f"{float(loss):.4f}"


def test_fit_rebinds_prefetch_sharding_per_fit():
    x, y = _xy(16)
    ds = TensorDataset([paddle.to_tensor(x), paddle.to_tensor(y)])
    loader = DataLoader(ds, batch_size=8, prefetch_to_device=2)

    def fresh_model():
        paddle.seed(0)
        m = paddle.Model(nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                                       nn.Linear(16, 4)))
        m.prepare(opt.AdamW(learning_rate=1e-2,
                            parameters=m.network.parameters()),
                  lambda a, b: nn.functional.mse_loss(a, b))
        return m

    # during fit the fn tracks the LIVE step, even across the step
    # recreation a mid-fit evaluate() causes — never a dead step whose
    # device state it would pin
    owners = []

    class _CaptureBinding(paddle.callbacks.Callback):
        def on_train_batch_begin(self, step, logs=None):
            if step == 0:  # binding happens between on_epoch_begin and
                owners.append((loader._batch_sharding_fn.__self__,
                               self.model._train_step))  # the first batch

    m1 = fresh_model()
    m1.fit(loader, eval_data=ds, epochs=2, verbose=0,
           callbacks=[_CaptureBinding()])
    assert len(owners) == 2
    assert all(fn_owner is live for fn_owner, live in owners)
    assert owners[0][0] is not owners[1][0]  # eval recreated the step
    # and fit unbinds on the way out: a loader that outlives the model
    # pins nothing
    assert loader._batch_sharding_fn is None
    # an explicitly user-set fn survives fit untouched
    marker = lambda a: None
    loader.set_batch_sharding(marker)
    m3 = fresh_model()
    m3.fit(loader, epochs=1, verbose=0)
    assert loader._batch_sharding_fn is marker


def test_visualdl_buffers_deferred_losses(tmp_path):
    x, y = _xy(32)
    ds = TensorDataset([paddle.to_tensor(x), paddle.to_tensor(y)])
    paddle.seed(0)
    m = paddle.Model(nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                                   nn.Linear(16, 4)))
    m.prepare(opt.AdamW(learning_rate=1e-2,
                        parameters=m.network.parameters()),
              lambda a, b: nn.functional.mse_loss(a, b))
    vdl = paddle.callbacks.VisualDL(log_dir=str(tmp_path))
    unresolved = []

    class _Probe(paddle.callbacks.Callback):
        def on_train_batch_end(self, step, logs=None):
            unresolved.append(logs["loss"][0]._resolved is None)

    m.fit(ds, batch_size=8, epochs=1, shuffle=False, verbose=0,
          callbacks=[vdl, _Probe()])
    # VisualDL held the handles mid-epoch (no per-step host sync)...
    assert unresolved and all(unresolved)
    # ...and drained real floats at epoch end
    import json
    with open(os.path.join(str(tmp_path), "scalars.jsonl")) as f:
        lines = [json.loads(line) for line in f if line.strip()]
    assert len(lines) == 4
    assert all(isinstance(rec["loss"], float) for rec in lines)


def test_fit_accumulate_grad_batches_single_update_per_k():
    x, y = _xy(32)
    ds = TensorDataset([paddle.to_tensor(x), paddle.to_tensor(y)])

    def fit_model(batch_size, k):
        paddle.seed(0)
        m = paddle.Model(nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                                       nn.Linear(16, 4)))
        m.prepare(opt.AdamW(learning_rate=1e-2,
                            parameters=m.network.parameters()),
                  lambda a, b: nn.functional.mse_loss(a, b))
        m.fit(ds, batch_size=batch_size, epochs=1, shuffle=False,
              verbose=0, accumulate_grad_batches=k)
        return m

    acc = fit_model(batch_size=4, k=2)
    # 8 loader batches folded 2-at-a-time -> exactly 4 optimizer updates
    assert acc._train_step._step_i == 4
    big = fit_model(batch_size=8, k=1)
    assert big._train_step._step_i == 4
    np.testing.assert_allclose(
        np.asarray(acc._train_step.params["0.weight"]),
        np.asarray(big._train_step.params["0.weight"]),
        rtol=1e-5, atol=1e-6)


# -- overlap: the ring + deferred losses hide host latency -------------

class _SlowBatchDataset(Dataset):
    """Batch assembly with a fixed artificial host latency per batch
    (the sleep lives in collate, so one sleep per batch exactly)."""

    def __init__(self, x, y):
        self.x, self.y = x, y

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


def _slow_collate(delay):
    from paddle_tpu.io import default_collate_fn

    def collate(samples):
        time.sleep(delay)
        return default_collate_fn(samples)
    return collate


class _ResolveEveryBatch(paddle.callbacks.Callback):
    """The OLD fit behavior: block the host on every step's loss."""

    def on_train_batch_end(self, step, logs=None):
        [float(v) for v in (logs or {}).get("loss", [])]


@pytest.mark.heavy
def test_overlap_ring_and_deferred_loss_beat_sync_path():
    dim, batch, nb = 1024, 128, 10
    rng = np.random.RandomState(0)
    x = rng.randn(batch * nb, dim).astype(np.float32)
    y = rng.randn(batch * nb, dim).astype(np.float32)

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(dim, dim), nn.Tanh(),
                        nn.Linear(dim, dim))
    model = paddle.Model(net)
    model.prepare(opt.AdamW(learning_rate=1e-3,
                            parameters=net.parameters()),
                  lambda a, b: nn.functional.mse_loss(a, b))

    model._ensure_train_step()
    step = model._train_step
    xb = paddle.to_tensor(x[:batch])
    yb = paddle.to_tensor(y[:batch])
    float(step(xb, yb))  # compile

    def run(prefetch, callbacks, delay):
        ds = _SlowBatchDataset(x, y)
        loader = DataLoader(ds, batch_size=batch, shuffle=False,
                            drop_last=True,
                            collate_fn=_slow_collate(delay),
                            prefetch_to_device=3 if prefetch else 0)
        # quiesce before the clock starts, drain before it stops: each
        # measurement owns exactly its epoch's device work
        jax.block_until_ready(model._train_step.params)
        t0 = time.perf_counter()
        model.fit(loader, epochs=1, verbose=0, callbacks=callbacks)
        jax.block_until_ready(model._train_step.params)
        return time.perf_counter() - t0

    # wall-clock assertion on a shared 2-core CPU: up to 3 rounds, each
    # freshly calibrated (contention drifts over a suite run — a stale
    # step-time estimate mis-sizes the latency and fakes a loss); one
    # clean round proves the overlap, a real regression fails all three
    for attempt in range(3):
        # calibrate the artificial host latency to the CURRENT synced
        # step time: ~60% of it, floored above fixed per-batch overheads
        # — long enough that hiding it dominates, short enough that the
        # producer thread stays ahead of the consumer
        t0 = time.perf_counter()
        for _ in range(3):
            l = step(xb, yb)
        float(l)
        c_sync = (time.perf_counter() - t0) / 3
        delay = max(0.02, 0.6 * c_sync)
        t_sync = run(prefetch=False, callbacks=[_ResolveEveryBatch()],
                     delay=delay)
        statistic.reset_statistics()
        t_async = run(prefetch=True, callbacks=None, delay=delay)
        waits = statistic.get_events("dataloader.next")
        assert waits, "dataloader.next span missing"
        total_wait = sum(w["total_s"] for w in waits)
        if t_sync / t_async >= 1.3 and total_wait < 0.5 * (nb * delay):
            break
    else:
        # sync pays (data + compute + fetch) per batch; async overlaps
        # data assembly/H2D with compute and fetches once per epoch —
        # and steady state the ring keeps the step loop fed, so the
        # consumer-side dataloader.next wait stays a small fraction of
        # the host latency the producer thread absorbed
        raise AssertionError(
            f"overlap not proven after 3 rounds: sync={t_sync:.3f}s "
            f"async={t_async:.3f}s (ratio {t_sync / t_async:.2f}, need "
            f">=1.3); dataloader.next={total_wait:.3f}s of "
            f"{nb * delay:.3f}s host latency (need <50% visible); "
            f"step={c_sync * 1000:.1f}ms delay={delay * 1000:.1f}ms")


# -- the no-hot-sync fence ---------------------------------------------

def _load_lint_tool():
    path = os.path.join(REPO, "tools", "check_no_hot_sync.py")
    spec = importlib.util.spec_from_file_location("check_no_hot_sync",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_no_hot_sync_lint_passes_on_repo():
    tool = _load_lint_tool()
    assert tool.main([REPO]) == 0


def test_no_hot_sync_lint_catches_violations():
    tool = _load_lint_tool()
    src = '\n'.join([
        "class TrainStep:",
        "    def __call__(self, *batch):",
        "        loss = self._jitted(*batch)",
        "        return " + "float(loss.item())",
        "    def other(self):",
        "        return " + "float(1.0)  # not a hot region",
    ])
    errs = tool.check_source(src, ["TrainStep.__call__"], "x.py")
    assert len(errs) == 2  # float( AND .item() on the hot line
    ok = src.replace("float(loss.item())",
                     "float(loss.item())  # hot" + "-sync-ok: test")
    assert tool.check_source(ok, ["TrainStep.__call__"], "x.py") == []
    # a renamed/missing region is itself a violation
    assert tool.check_source(src, ["TrainStep.gone"], "x.py")


def test_predict_handles_bare_and_labeled_batches():
    class Bare(Dataset):
        def __getitem__(self, i):
            return np.arange(8, dtype=np.float32) + i

        def __len__(self):
            return 8

    paddle.seed(0)
    net = nn.Linear(8, 3)
    m = paddle.Model(net)
    # bare batch: collate yields ONE Tensor, not a list — must be
    # wrapped, not sliced
    outs = m.predict(Bare(), batch_size=4, stack_outputs=True)
    assert outs[0].shape == (8, 3)
    # labeled batch: trailing label field is dropped before forward
    x, y = _xy(8)
    ds = TensorDataset([paddle.to_tensor(x),
                        paddle.to_tensor(y[:, :1])])
    outs2 = m.predict(ds, batch_size=4, stack_outputs=True)
    assert outs2[0].shape == (8, 3)
