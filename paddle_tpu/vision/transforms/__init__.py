"""paddle.vision.transforms. Parity: python/paddle/vision/transforms/.
Numpy/HWC-based functional + class transforms (CHW output via ToTensor)."""
import collections.abc
import numbers
import random

import numpy as np

from ...framework.core import Tensor

__all__ = ["Compose", "BaseTransform", "ToTensor", "Resize", "CenterCrop",
           "RandomCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "RandomRotation", "RandomResizedCrop", "ColorJitter",
           "Normalize", "Pad", "Grayscale", "BrightnessTransform",
           "ContrastTransform", "SaturationTransform", "HueTransform",
           "Transpose", "to_tensor", "resize", "center_crop", "crop",
           "hflip", "vflip", "normalize", "pad", "rotate", "to_grayscale",
           "adjust_brightness", "adjust_contrast", "adjust_hue", "erase"]


def _hwc(img):
    if isinstance(img, Tensor):
        img = img.numpy()
    arr = np.asarray(img)
    return arr


# ---------------- functional ----------------
def to_tensor(pic, data_format="CHW"):
    arr = _hwc(pic).astype(np.float32)
    if arr.max() > 1.5:
        arr = arr / 255.0
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return Tensor(arr)


def resize(img, size, interpolation="bilinear"):
    arr = _hwc(img)
    if isinstance(size, int):
        h, w = arr.shape[:2]
        if h < w:
            oh, ow = size, int(size * w / h)
        else:
            oh, ow = int(size * h / w), size
    else:
        oh, ow = size
    # separable linear resize in numpy (no PIL dependency)
    def interp_axis(a, out_len, axis):
        in_len = a.shape[axis]
        if in_len == out_len:
            return a
        pos = np.linspace(0, in_len - 1, out_len)
        lo = np.floor(pos).astype(np.int64)
        hi = np.minimum(lo + 1, in_len - 1)
        w = (pos - lo).reshape([-1 if i == axis else 1
                                for i in range(a.ndim)])
        return np.take(a, lo, axis=axis) * (1 - w) + \
            np.take(a, hi, axis=axis) * w
    out = interp_axis(arr.astype(np.float32), oh, 0)
    out = interp_axis(out, ow, 1)
    return out.astype(arr.dtype)


def crop(img, top, left, height, width):
    return _hwc(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    arr = _hwc(img)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    th, tw = output_size
    h, w = arr.shape[:2]
    i = max((h - th) // 2, 0)
    j = max((w - tw) // 2, 0)
    return crop(arr, i, j, th, tw)


def hflip(img):
    return _hwc(img)[:, ::-1]


def vflip(img):
    return _hwc(img)[::-1]


def pad(img, padding, fill=0, padding_mode="constant"):
    arr = _hwc(img)
    if isinstance(padding, int):
        padding = [padding] * 4
    if len(padding) == 2:
        padding = [padding[0], padding[1], padding[0], padding[1]]
    l, t, r, b = padding
    widths = [(t, b), (l, r)] + [(0, 0)] * (arr.ndim - 2)
    if padding_mode == "constant":
        return np.pad(arr, widths, mode="constant", constant_values=fill)
    mode = {"reflect": "reflect", "edge": "edge",
            "symmetric": "symmetric"}[padding_mode]
    return np.pad(arr, widths, mode=mode)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    arr = _hwc(img).astype(np.float32)
    h, w = arr.shape[:2]
    cy, cx = ((h - 1) / 2, (w - 1) / 2) if center is None \
        else (center[1], center[0])
    rad = -np.deg2rad(angle)
    cos, sin = np.cos(rad), np.sin(rad)
    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    ys = cos * (yy - cy) - sin * (xx - cx) + cy
    xs = sin * (yy - cy) + cos * (xx - cx) + cx
    yi = np.round(ys).astype(np.int64)
    xi = np.round(xs).astype(np.int64)
    valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
    out = np.full_like(arr, fill)
    out[valid] = arr[yi[valid], xi[valid]]
    return out.astype(_hwc(img).dtype)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    arr = np.asarray(img.numpy() if isinstance(img, Tensor) else img,
                     dtype=np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        mean = mean.reshape(-1, 1, 1)
        std = std.reshape(-1, 1, 1)
    out = (arr - mean) / std
    return Tensor(out) if isinstance(img, Tensor) else out


def to_grayscale(img, num_output_channels=1):
    arr = _hwc(img).astype(np.float32)
    gray = arr[..., 0] * 0.299 + arr[..., 1] * 0.587 + arr[..., 2] * 0.114
    out = np.repeat(gray[..., None], num_output_channels, -1)
    return out.astype(_hwc(img).dtype)


def adjust_brightness(img, factor):
    arr = _hwc(img).astype(np.float32) * factor
    return np.clip(arr, 0, 255).astype(_hwc(img).dtype)


def adjust_contrast(img, factor):
    arr = _hwc(img).astype(np.float32)
    mean = to_grayscale(arr).mean()
    out = (arr - mean) * factor + mean
    return np.clip(out, 0, 255).astype(_hwc(img).dtype)


def adjust_hue(img, factor):
    arr = _hwc(img).astype(np.float32) / 255.0
    # quick RGB→HSV hue shift
    maxc = arr.max(-1)
    minc = arr.min(-1)
    v = maxc
    delta = maxc - minc + 1e-8
    s = delta / (maxc + 1e-8)
    r, g, b = arr[..., 0], arr[..., 1], arr[..., 2]
    h = np.where(maxc == r, (g - b) / delta % 6,
                 np.where(maxc == g, (b - r) / delta + 2,
                          (r - g) / delta + 4)) / 6.0
    h = (h + factor) % 1.0
    i = (h * 6).astype(np.int64) % 6
    f = h * 6 - np.floor(h * 6)
    p, q, t = v * (1 - s), v * (1 - f * s), v * (1 - (1 - f) * s)
    lut = [np.stack([v, t, p], -1), np.stack([q, v, p], -1),
           np.stack([p, v, t], -1), np.stack([p, q, v], -1),
           np.stack([t, p, v], -1), np.stack([v, p, q], -1)]
    # conditions are [H, W]; broadcast against the [H, W, 3] choices
    out = np.select([(i == k)[..., None] for k in range(6)], lut)
    return np.clip(out * 255, 0, 255).astype(_hwc(img).dtype)


def erase(img, i, j, h, w, v, inplace=False):
    if isinstance(img, Tensor):
        arr = np.array(img.numpy())
        arr[..., i:i + h, j:j + w] = v
        out = Tensor(arr)
        if inplace:
            img._bind(out._slot)
            return img
        return out
    arr = np.array(img)
    arr[i:i + h, j:j + w] = v
    return arr


# ---------------- class transforms ----------------
class BaseTransform:
    """Reference protocol (vision/transforms/transforms.py
    BaseTransform): multi-field transforms dispatch per `keys` entry to
    `_apply_<key>`, with `self.params = self._get_params(inputs)` set
    before the per-key application so custom subclasses can share
    randomness across fields (the CustomRandomFlip doc example)."""

    def __init__(self, keys=None):
        if keys is None:
            keys = ("image",)
        elif not isinstance(keys, collections.abc.Sequence):
            raise ValueError(f"keys should be a sequence, got {keys!r}")
        self.keys = keys

    def _get_params(self, inputs):
        return None

    def __call__(self, inputs):
        if isinstance(inputs, tuple):
            args = inputs
        else:
            args = (inputs,)
        self.params = self._get_params(args)
        outputs = []
        for i in range(min(len(args), len(self.keys))):
            apply_func = getattr(self, f"_apply_{self.keys[i]}",
                                 None)
            outputs.append(args[i] if apply_func is None
                           else apply_func(args[i]))
        outputs.extend(args[len(self.keys):])
        if len(outputs) == 1:
            return outputs[0]
        return tuple(outputs)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor(img, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else size
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        arr = _hwc(img)
        if self.padding is not None:
            arr = pad(arr, self.padding, self.fill, self.padding_mode)
        th, tw = self.size
        h, w = arr.shape[:2]
        if self.pad_if_needed and (h < th or w < tw):
            arr = pad(arr, [max(tw - w, 0), max(th - h, 0)], self.fill,
                      self.padding_mode)
            h, w = arr.shape[:2]
        i = random.randint(0, h - th)
        j = random.randint(0, w - tw)
        return crop(arr, i, j, th, tw)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return hflip(img) if random.random() < self.prob else _hwc(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return vflip(img) if random.random() < self.prob else _hwc(img)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.kw = dict(interpolation=interpolation, expand=expand,
                       center=center, fill=fill)

    def _apply_image(self, img):
        angle = random.uniform(*self.degrees)
        return rotate(img, angle, **self.kw)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else size
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        arr = _hwc(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = random.uniform(*self.ratio)
            cw = int(round((target * ar) ** 0.5))
            ch = int(round((target / ar) ** 0.5))
            if cw <= w and ch <= h:
                i = random.randint(0, h - ch)
                j = random.randint(0, w - cw)
                return resize(crop(arr, i, j, ch, cw), self.size,
                              self.interpolation)
        return resize(center_crop(arr, min(h, w)), self.size,
                      self.interpolation)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        super().__init__(keys)
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean, self.std = mean, std
        self.data_format = data_format

    def _apply_image(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return _hwc(img)
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_brightness(img, f)


class ContrastTransform(BrightnessTransform):
    def _apply_image(self, img):
        if self.value == 0:
            return _hwc(img)
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_contrast(img, f)


class SaturationTransform(BrightnessTransform):
    def _apply_image(self, img):
        if self.value == 0:
            return _hwc(img)
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        gray = to_grayscale(img, 3).astype(np.float32)
        arr = _hwc(img).astype(np.float32)
        out = arr * f + gray * (1 - f)
        return np.clip(out, 0, 255).astype(_hwc(img).dtype)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return _hwc(img)
        return adjust_hue(img, random.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.ts = [BrightnessTransform(brightness),
                   ContrastTransform(contrast),
                   SaturationTransform(saturation), HueTransform(hue)]

    def _apply_image(self, img):
        ts = list(self.ts)
        random.shuffle(ts)
        for t in ts:
            img = t(img)
        return img


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        return _hwc(img).transpose(self.order)
