"""Two-process distributed integration: launch -> collective -> DP step.

VERDICT r3 #5: `paddle_tpu.distributed.launch` must be PROVEN, not just
plausible — this spawns 2 REAL processes on the CPU backend, each joining
a jax.distributed world over a loopback coordinator (the exact mechanism
a TPU pod uses over DCN), runs a cross-process psum and a data-parallel
train step, and asserts cross-process agreement.

Parity: python/paddle/distributed/launch.py (the reference's
multi-process launcher + NCCL world bootstrap).
"""
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "_launch_worker.py")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_launch(tmp_path):
    port = _free_port()
    env_base = {k: v for k, v in os.environ.items()
                if not k.startswith(("XLA_", "JAX_"))}
    env_base["PYTHONPATH"] = REPO
    # pin the CPU backend BEFORE the launcher module imports jax — the
    # axon TPU plugin would otherwise initialize the backend and break
    # jax.distributed.initialize ordering
    env_base["JAX_PLATFORMS"] = "cpu"
    env_base["PALLAS_AXON_POOL_IPS"] = ""
    procs = []
    for rank in (0, 1):
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nnodes", "2", "--node_rank", str(rank),
             "--master", f"127.0.0.1:{port}",
             WORKER, str(tmp_path)],
            env=env_base, cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("launch worker timed out")
        outs.append(out.decode(errors="replace"))
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"

    results = {}
    for rank in (0, 1):
        with open(tmp_path / f"rank{rank}.json") as f:
            results[rank] = json.load(f)

    for rank in (0, 1):
        r = results[rank]
        assert r["world"] == 2
        # psum over both processes: 0 + 1
        assert r["psum"] == pytest.approx(1.0)
        assert r["losses"][-1] < r["losses"][0]
    # the DP-trained parameters must be bit-identical across processes
    # (same replicated update on both ranks after the grad psum)
    np.testing.assert_array_equal(np.asarray(results[0]["w"]),
                                  np.asarray(results[1]["w"]))
    # and both ranks observed the same loss trajectory
    assert results[0]["losses"] == results[1]["losses"]
