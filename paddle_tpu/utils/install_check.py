"""paddle.utils.install_check — post-install smoke test.

Parity: /root/reference/python/paddle/utils/install_check.py. Runs a
tiny linear-regression train step three ways — eager, static
(Executor), and data-parallel across every visible device via a
sharded batch — and prints the reference's familiar confirmation
lines.
"""
import numpy as np

__all__ = []


def _simple_network():
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn

    class SimpleNet(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(16, 4)

        def forward(self, x):
            return self.fc(x)

    return SimpleNet()


def _train_data():
    rng = np.random.RandomState(0)
    x = rng.rand(8, 16).astype(np.float32)
    y = rng.rand(8, 4).astype(np.float32)
    return x, y


def _run_dygraph_single():
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    paddle.disable_static()
    model = _simple_network()
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    x, y = _train_data()
    loss = nn.functional.mse_loss(model(paddle.to_tensor(x)),
                                  paddle.to_tensor(y))
    loss.backward()
    opt.step()
    opt.clear_grad()
    return float(loss.item())


def _run_static_single():
    import paddle_tpu as paddle
    from paddle_tpu import static
    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x_in = static.data("x", [None, 16], "float32")
            y_in = static.data("y", [None, 4], "float32")
            out = static.nn.fc(x_in, 4)
            loss = paddle.mean((out - y_in) ** 2)
            paddle.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = static.Executor()
        exe.run(startup)
        x, y = _train_data()
        (lv,) = exe.run(main, feed={"x": x, "y": y}, fetch_list=[loss])
        return float(np.asarray(lv).reshape(-1)[0])
    finally:
        paddle.disable_static()


def _run_parallel():
    """One jitted step with the batch sharded across every device —
    the TPU equivalent of the reference's multi-GPU fleet check."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    n = jax.device_count()
    if n < 2:
        return None
    mesh = Mesh(np.array(jax.devices()), ("dp",))
    w = jnp.zeros((16, 4), jnp.float32)
    x, y = _train_data()
    x = jnp.asarray(np.tile(x, (n, 1)))
    y = jnp.asarray(np.tile(y, (n, 1)))
    x = jax.device_put(x, NamedSharding(mesh, P("dp", None)))
    y = jax.device_put(y, NamedSharding(mesh, P("dp", None)))

    @jax.jit
    def step(w, x, y):
        def loss_fn(w):
            return jnp.mean((x @ w - y) ** 2)
        loss, g = jax.value_and_grad(loss_fn)(w)
        return loss, w - 0.1 * g

    loss, _ = step(w, x, y)
    return float(loss)


def run_check():
    """Smoke-check the installation; mirrors the reference's output."""
    import jax
    n = jax.device_count()
    backend = jax.default_backend()
    print(f"Running verify PaddlePaddle(TPU) program ... ")
    _run_dygraph_single()
    _run_static_single()
    parallel = _run_parallel()
    if parallel is not None:
        print(f"PaddlePaddle(TPU) works well on {n} {backend} devices.")
    print(f"PaddlePaddle(TPU) works well on 1 {backend} device.")
    print("PaddlePaddle(TPU) is installed successfully! Let's start "
          "deep learning with PaddlePaddle(TPU) now.")
    return True
