from .metric import sum, max, min, auc, mae, rmse, mse, acc  # noqa: F401,A004
