"""Edge-case op semantics vs numpy oracles — the op_test.py-style corner
coverage the reference's unittests sweep (0-d, empty, broadcasting,
dtype promotion, negative axes, nan propagation)."""
import numpy as np
import pytest

import paddle_tpu as paddle


class TestZeroDim:
    def test_scalar_tensor_ops(self):
        a = paddle.to_tensor(3.0)
        b = paddle.to_tensor(4.0)
        assert a.shape == []
        assert float((a * b).item()) == 12.0
        assert (a + b).shape == []
        assert float(a.sqrt().item()) == pytest.approx(np.sqrt(3.0))

    def test_scalar_reduction_and_grad(self):
        x = paddle.to_tensor(2.0, stop_gradient=False)
        y = x * x
        y.backward()
        assert float(x.grad.item()) == 4.0

    def test_zero_dim_broadcast(self):
        s = paddle.to_tensor(2.0)
        m = paddle.to_tensor(np.ones((2, 3), np.float32))
        np.testing.assert_allclose((s * m).numpy(), 2 * np.ones((2, 3)))


class TestEmptyTensors:
    def test_empty_creation_and_concat(self):
        e = paddle.to_tensor(np.zeros((0, 4), np.float32))
        assert e.shape == [0, 4]
        full = paddle.concat([e, paddle.ones([2, 4])], axis=0)
        assert full.shape == [2, 4]

    def test_empty_reductions(self):
        e = paddle.to_tensor(np.zeros((0,), np.float32))
        assert float(e.sum().item()) == 0.0
        assert bool(paddle.is_empty(e).item())

    def test_boolean_mask_can_be_empty(self):
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        m = paddle.to_tensor(np.array([False, False]))
        out = paddle.masked_select(x, m)
        assert out.shape == [0]


class TestBroadcasting:
    def test_matches_numpy_rules(self):
        rng = np.random.RandomState(0)
        cases = [((3, 1, 4), (2, 4)), ((1,), (5, 1)), ((2, 3), (3,)),
                 ((4, 1, 1), (1, 3, 5))]
        for sa, sb in cases:
            a = rng.randn(*sa).astype(np.float32)
            b = rng.randn(*sb).astype(np.float32)
            got = (paddle.to_tensor(a) + paddle.to_tensor(b)).numpy()
            np.testing.assert_allclose(got, a + b, rtol=1e-6)

    def test_incompatible_shapes_raise(self):
        a = paddle.to_tensor(np.ones((3, 2), np.float32))
        b = paddle.to_tensor(np.ones((3, 4), np.float32))
        with pytest.raises(Exception):
            (a + b).numpy()

    def test_broadcast_shape_api(self):
        assert paddle.broadcast_shape([3, 1, 4], [2, 4]) == [3, 2, 4]


class TestDtypeSemantics:
    def test_int_float_promotion_via_scalar(self):
        i = paddle.to_tensor(np.array([1, 2], np.int64))
        out = i * 2.5
        assert "float" in str(out.dtype)
        np.testing.assert_allclose(out.numpy(), [2.5, 5.0])

    def test_bool_tensor_logic(self):
        a = paddle.to_tensor(np.array([True, False]))
        b = paddle.to_tensor(np.array([True, True]))
        np.testing.assert_array_equal(
            paddle.logical_and(a, b).numpy(), [True, False])
        np.testing.assert_array_equal(
            paddle.logical_not(a).numpy(), [False, True])

    def test_cast_round_trip(self):
        x = paddle.to_tensor(np.array([1.7, -2.3], np.float32))
        i = x.cast("int32")
        assert i.numpy().dtype == np.int32
        np.testing.assert_array_equal(i.numpy(), [1, -2])  # trunc


class TestAxesAndKeepdim:
    def test_negative_axis_everywhere(self):
        x = paddle.to_tensor(np.arange(24, dtype=np.float32).reshape(2, 3, 4))
        np.testing.assert_allclose(x.sum(axis=-1).numpy(),
                                   x.numpy().sum(-1))
        np.testing.assert_allclose(x.max(axis=-2).numpy(),
                                   x.numpy().max(-2))
        assert x.unsqueeze(-1).shape == [2, 3, 4, 1]
        assert x.squeeze(-1).shape == [2, 3, 4]  # no-op (not size 1)

    def test_keepdim(self):
        x = paddle.to_tensor(np.ones((2, 3), np.float32))
        assert x.sum(axis=1, keepdim=True).shape == [2, 1]
        assert x.mean(axis=0, keepdim=False).shape == [3]


class TestNaNSemantics:
    def test_nan_propagation_and_nansum(self):
        x = paddle.to_tensor(np.array([1.0, np.nan, 2.0], np.float32))
        assert np.isnan(float(x.sum().item()))
        assert float(paddle.nansum(x).item()) == 3.0
        np.testing.assert_array_equal(paddle.isnan(x).numpy(),
                                      [False, True, False])

    def test_nan_in_max_min(self):
        x = paddle.to_tensor(np.array([1.0, np.nan], np.float32))
        # jnp/np semantics: nan wins max
        assert np.isnan(float(x.max().item()))
        assert float(paddle.fmax(
            paddle.to_tensor(np.array([np.nan], np.float32)),
            paddle.to_tensor(np.array([2.0], np.float32))).item()) == 2.0

    def test_isfinite_family(self):
        x = paddle.to_tensor(np.array([1.0, np.inf, -np.inf, np.nan],
                                      np.float32))
        np.testing.assert_array_equal(
            paddle.isfinite(x).numpy(), [True, False, False, False])
        np.testing.assert_array_equal(
            paddle.isinf(x).numpy(), [False, True, True, False])
