"""Hybrid-parallel jitted train step — the fleet execution engine.

The TPU-native replacement for the reference's HybridParallelOptimizer +
PipelineParallel + ShardingStage2 runtime classes (distributed/fleet/
meta_parallel/*): one jax.jit'ed SPMD program over the fleet mesh where

- batch is sharded over ('dp',)                       [data parallel]
- params follow per-layer PartitionSpecs over 'mp'    [tensor parallel]
- optimizer states are additionally sharded over the
  'sharding' axis (ZeRO-1/2)                          [sharding]
- blocks can be rematerialized (jax.checkpoint)       [recompute]
- gradient accumulation folds microbatches in a scan  [gradient_merge /
                                                       pipeline microbatch]

XLA inserts psum for dp grad sync (reference: reducer.cc fused allreduce),
allreduce/allgather for mp (reference: mp_allreduce), and reduce-scatter
for ZeRO — all over ICI.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...framework.core import Tensor, no_grad, _Slot
from ...framework.random import split_key
from ...jit.api import (functional_call, state_arrays, aot_compile,
                        count_train_use, export_step_metrics,
                        HealthMonitorMixin, _step_arg_names)
from ...jit import warm as _warm
from ...jit.deferred import DeferredLoss
from ...profiler import statistic as _stat
from ...profiler import monitor as _monitor
from ...profiler import cost as _cost
from ...profiler import flight_recorder as _flight

__all__ = ["HybridTrainStep", "default_param_rules"]


def default_param_rules(name, arr):
    """Name-based PartitionSpec rules for transformer-family models when a
    layer doesn't announce its own sharding_spec."""
    if arr.ndim == 2:
        if any(k in name for k in ("qkv_proj.weight", "fc_in.weight",
                                   "q_proj.weight", "k_proj.weight",
                                   "v_proj.weight", "linear1.weight")):
            return P(None, "mp")
        if any(k in name for k in ("out_proj.weight", "fc_out.weight",
                                   "linear2.weight")):
            return P("mp", None)
        if any(k in name for k in ("wte.weight", "embed_tokens.weight",
                                   "word_embeddings.weight")):
            return P("mp", None)
    if arr.ndim == 1 and any(k in name for k in ("qkv_proj.bias",
                                                 "fc_in.bias",
                                                 "linear1.bias")):
        return P("mp")
    return P()


def _collect_specs(model, params):
    """Layer-announced sharding_spec()s override the name rules."""
    specs = {}
    for lname, layer in model.named_sublayers(include_self=True):
        spec_fn = getattr(layer, "sharding_spec", None)
        if spec_fn is None:
            continue
        for pname, spec in spec_fn().items():
            full = f"{lname}.{pname}" if lname else pname
            specs[full] = spec
    out = {}
    for k, v in params.items():
        out[k] = specs.get(k, default_param_rules(k, v))
    return out


def _zero_spec(pspec, mesh, arr):
    """Extend a param spec with the 'sharding' axis on the first
    axis that is unsharded and divisible (ZeRO state placement)."""
    deg = mesh.shape.get("sharding", 1)
    if deg == 1:
        return pspec
    dims = list(pspec) + [None] * (arr.ndim - len(pspec))
    for i, d in enumerate(dims):
        if d is None and arr.shape[i] % deg == 0 and arr.shape[i] >= deg:
            dims[i] = "sharding"
            return P(*dims)
    return pspec


class HybridTrainStep(HealthMonitorMixin):
    """Build once, call per batch. See module docstring."""

    def __init__(self, model, loss_fn, optimizer, mesh, recompute=False,
                 accumulate_steps=1, donate=True, param_dtype=None,
                 sharding_stage=1, scaler=None, monitor_health=False):
        """sharding_stage selects the ZeRO behavior over the 'sharding'
        mesh axis (ref sharding/sharding_stage2.py:43, sharding_stage3.py:51):
          1 — optimizer state sharded (grads allreduced, params replicated)
          2 — + gradients pinned to the zero specs: the update runs on
              grad shards and the grad sync lowers to all-reduce+slice,
              which the TPU ReduceScatterCreator pass fuses into a true
              reduce-scatter (half the sync bytes); updated params
              all-gather back to their param specs
          3 — + parameters THEMSELVES stored sharded; XLA all-gathers
              weights at use sites and frees them after use

        monitor_health=True appends the training-health vector (global
        grad norm, param norm, update ratio — jit/api.py
        HealthMonitorMixin) to the compiled SPMD program, replicated
        over the mesh, resolved on the async is_ready-gated path."""
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.mesh = mesh
        self.accumulate_steps = accumulate_steps
        self.sharding_stage = int(sharding_stage)
        if self.sharding_stage not in (1, 2, 3):
            raise ValueError(f"sharding_stage must be 1|2|3, got "
                             f"{sharding_stage}")
        self._step_i = 0
        # GradScaler state rides inside the compiled step (donated, like
        # params/opt state); replicated over the mesh
        self.scaler = scaler
        self.scaler_state = scaler.init_jit_state() if scaler is not None \
            else {}
        self.retraces = 0
        self.compile_s = 0.0
        self.last_compile_s = None
        self._init_health(monitor_health)

        params, buffers = state_arrays(model)
        if param_dtype is not None:
            from ...framework.dtype import convert_dtype
            dt = convert_dtype(param_dtype)
            params = {k: v.astype(dt) if jnp.issubdtype(
                v.dtype, jnp.floating) else v for k, v in params.items()}
        self.param_specs = _collect_specs(model, params)
        self.zero_specs = {
            k: _zero_spec(self.param_specs[k], mesh, v)
            for k, v in params.items()}
        # stage 3: parameters live sharded over 'sharding'; XLA
        # all-gathers them at use sites (ZeRO-3 param partitioning)
        store_specs = self.zero_specs if self.sharding_stage >= 3 \
            else self.param_specs
        self.param_shardings = {
            k: NamedSharding(mesh, store_specs[k])
            for k in self.param_specs}
        self.params = {
            k: jax.device_put(v, self.param_shardings[k])
            for k, v in params.items()}
        self.buffers = buffers

        # optimizer state: param spec + ZeRO sharding axis
        def init_state(k, v):
            # init_leaf_state may wrap the tuple with an f32 master copy
            # (multi_precision); master/state leaves all share the param's
            # ZeRO sharding (same shapes)
            st = optimizer.init_leaf_state(v)
            sh = NamedSharding(mesh, _zero_spec(self.param_specs[k], mesh,
                                                v))
            return jax.tree.map(lambda s: jax.device_put(s, sh), st)
        self.opt_state = {k: init_state(k, v)
                          for k, v in self.params.items()}

        # batch dim over dp; with a sequence-parallel mesh (sp>1), the
        # sequence dim is sharded over 'sp' too — ring attention inside
        # the model consumes it without gathering (long-context path)
        sp_deg = mesh.shape.get("sp", 1)
        self.batch_sharding = NamedSharding(
            mesh, P(("dp",), "sp") if sp_deg > 1 else P(("dp",)))
        self._dp_only = NamedSharding(mesh, P(("dp",)))
        loss_sharding = NamedSharding(mesh, P())

        model_ref = model
        opt = optimizer
        stage = self.sharding_stage
        zero_shardings = {k: NamedSharding(mesh, s)
                          for k, s in self.zero_specs.items()}

        def loss_of(ps, bufs, key, micro):
            def run(inputs):
                from ...jit.api import (reset_aux_losses,
                                        collect_aux_losses)
                reset_aux_losses(model_ref)
                out = functional_call(model_ref, ps, bufs, inputs[:-1],
                                      rng_key=key, training=True)
                tgt = Tensor(inputs[-1])
                l = loss_fn(out if isinstance(out, Tensor) else Tensor(out),
                            tgt)
                l = l.value if isinstance(l, Tensor) else l
                aux = collect_aux_losses(model_ref)
                return l if aux is None else l + aux.astype(l.dtype)
            if recompute:
                run = jax.checkpoint(run)
            return run(micro)

        scaler_ref = scaler
        mon_health = self.monitor_health

        def step_fn(params_, opt_state_, scaler_state_, bufs, key, lr,
                    step_i, *batch):
            scaling = scaler_ref is not None and scaler_ref.is_enable()
            scale = scaler_state_["scale"] if scaling else None

            def objective(ps, micro):
                l = loss_of(ps, bufs, key, micro)
                return l.astype(jnp.float32) * scale if scaling else l

            if accumulate_steps > 1:
                micros = [jnp.stack(jnp.split(b, accumulate_steps, axis=0))
                          for b in batch]

                def acc_body(carry, micro):
                    loss_sum, grads_sum = carry
                    l, g = jax.value_and_grad(
                        lambda ps: objective(ps, micro))(params_)
                    return (loss_sum + l,
                            jax.tree.map(jnp.add, grads_sum, g)), None

                zeros = jax.tree.map(jnp.zeros_like, params_)
                (loss_sum, grads), _ = jax.lax.scan(
                    acc_body, (jnp.zeros((), jnp.float32), zeros),
                    tuple(micros))
                loss = loss_sum / accumulate_steps
                grads = jax.tree.map(lambda g: g / accumulate_steps, grads)
            else:
                loss, grads = jax.value_and_grad(
                    lambda ps: objective(ps, batch))(params_)

            # the health vector norms the RAW (possibly scale-multiplied)
            # grads — _health_vec unscales by division, so a non-finite
            # gradient stays visible as a non-finite grad_norm
            raw_grads = grads if mon_health else None
            if scaling:
                loss = loss / scale
                grads, found_inf, new_scaler_state = \
                    scaler_ref.jit_unscale_and_update(scaler_state_, grads)
            else:
                found_inf, new_scaler_state = None, scaler_state_

            if stage >= 2:
                # ZeRO-2: pin gradients to the zero specs — the SPMD
                # partitioner then lowers dp grad sync as reduce-scatter
                # (each rank keeps only its grad shard) instead of
                # all-reduce, and the optimizer update below runs on
                # shards (ref sharding_stage2.py:43)
                grads = jax.lax.with_sharding_constraint(grads,
                                                         zero_shardings)

            from ...nn.clip import clip_grads_tree
            grads = clip_grads_tree(grads, opt._grad_clip)
            new_params, new_state = opt.apply_gradients_tree(
                params_, grads, opt_state_, lr, step_i,
                found_inf=found_inf)
            if mon_health:
                health = self._health_vec(loss, raw_grads, scaler_state_,
                                          params_, new_params)
                return loss, health, new_params, new_state, \
                    new_scaler_state
            return loss, new_params, new_state, new_scaler_state

        # mirror each state leaf's structure (tuple, or the
        # {master, state} dict init_leaf_state builds for multi_precision)
        state_shardings = {
            k: jax.tree.map(
                lambda _s, _sh=NamedSharding(
                    mesh, _zero_spec(self.param_specs[k], mesh,
                                     self.params[k])): _sh,
                self.opt_state[k])
            for k in self.opt_state}
        scaler_shardings = jax.tree.map(
            lambda _: NamedSharding(mesh, P()), self.scaler_state)
        out_shardings = (loss_sharding, self.param_shardings,
                         state_shardings, scaler_shardings)
        if mon_health:  # health vector rides replicated, like the loss
            out_shardings = (loss_sharding, NamedSharding(mesh, P()),
                             *out_shardings[1:])
        self._jitted = jax.jit(
            step_fn,
            donate_argnums=(0, 1, 2) if donate else (),
            out_shardings=out_shardings)
        # AOT executables keyed by batch signature (jit.api.aot_compile):
        # trace/compile phases timed, persistent-cache hit observed,
        # cost_analysis free
        self._exec = {}

    def input_sharding(self, arr):
        """Sharding the compiled step expects for a batch leaf (batch dim
        over 'dp', sequence over 'sp' when sequence-parallel). The device
        prefetch ring (io/device_prefetch.py) places H2D copies with this
        while the previous step computes, so `_prep` below finds the
        arrays already resident and sharded."""
        return self.batch_sharding if arr.ndim >= 2 else self._dp_only

    def _prep(self, batch, step_i):
        """(sig, full arg tuple) for one dispatch — the ONE place the
        batch is sharded and the signature built: __call__ and the
        inspection paths must agree exactly, because the cached
        executable bakes the input shardings. An array that already
        carries its target sharding (prefetch ring) passes through
        without a fresh device_put."""
        arrays = []
        for b in batch:
            a = b.value if isinstance(b, Tensor) else jnp.asarray(b)
            sh = self.input_sharding(a)
            if getattr(a, "sharding", None) != sh:
                a = jax.device_put(a, sh)
            arrays.append(a)
        sig = tuple((tuple(a.shape), str(a.dtype)) for a in arrays)
        args = (self.params, self.opt_state, self.scaler_state,
                self.buffers, split_key(),
                jnp.asarray(self.optimizer.get_lr(), jnp.float32),
                step_i, *arrays)
        return sig, args

    def _warm_submit(self, sig, args, n_batch, inline=False):
        """Single-flight compile of this signature's SPMD executable
        (jit/warm.py submit_cached) — shared by `warm()` (background)
        and the dispatch/inspection paths (`inline=True`: compile on
        the calling thread rather than queue behind background warms),
        so a warm in flight is always joined, never duplicated."""
        return _warm.submit_cached(
            self._exec, sig, "fleet.hybrid_step",
            lambda: aot_compile(self._jitted, args,
                                tag="fleet.hybrid_step",
                                arg_names=_step_arg_names(n_batch)),
            inline=inline)

    def warm(self, *batch):
        """Start a BACKGROUND AOT compile of the hybrid SPMD executable
        for exactly this batch signature (same `_prep`, same shardings
        and donation as dispatch — warming adds zero executables beyond
        steady state) and return a `jit.warm.WarmHandle`. The first
        `__call__` with this signature joins the in-flight compile."""
        sig, args = self._prep(batch, self._step_i + 1)
        return self._warm_submit(sig, args, len(batch))

    def __call__(self, *batch):
        self._step_i += 1
        sig, args = self._prep(batch, self._step_i)
        _flight.heartbeat(self._step_i)  # watchdog liveness pulse
        _stat.begin_span("fleet.hybrid_step")
        try:
            entry = self._exec.get(sig)
            compiled_now = entry is None
            if compiled_now:
                entry = self._warm_submit(sig, args, len(batch),
                                          inline=True).result()
            compiled, info = entry
            count_train_use(self, info)
            try:
                out = compiled(*args)
            except (FloatingPointError, RuntimeError) as e:
                # jax_debug_nans found a non-finite value: flight-record
                # and write a debug bundle before re-raising (same
                # contract as TrainStep._dispatch, incl. the donated-
                # buffer re-run surfacing as a deleted-array error)
                donated_rerun = (
                    isinstance(e, RuntimeError)
                    and jax.config.jax_debug_nans
                    and "deleted" in str(e))
                if isinstance(e, RuntimeError) and not donated_rerun:
                    raise
                _flight.record_event("nan_detected",
                                     where="fleet.hybrid_step",
                                     step=int(self._step_i),
                                     error=str(e)[:300])
                _flight.dump("nan", exc=e)
                if donated_rerun:
                    raise FloatingPointError(
                        "jax_debug_nans detected a non-finite value in "
                        "the compiled fleet.hybrid_step program (the "
                        "op-level re-run could not localize it because "
                        "the step donates its buffers; build with "
                        "donate=False to localize)") from e
                raise
            if self.monitor_health:
                loss, health, self.params, self.opt_state, \
                    self.scaler_state = out
                self._queue_health(self._step_i, health)
            else:
                loss, self.params, self.opt_state, self.scaler_state = out
        finally:
            dispatch_s = _stat.end_span()
        export_step_metrics(self, dispatch_s, info, compiled_now)
        # non-blocking handle (see jit/deferred.py): the fit loop keeps
        # dispatching while the loss streams back
        return DeferredLoss(loss)

    def cost_analysis(self, *batch):
        """XLA cost report for this batch signature's SPMD executable
        (per-device flops/bytes; free once the step has run, and never
        touching the retrace counters)."""
        return _cost.cost_analysis(self._executable(*batch))

    def flops(self, *batch):
        """Per-step per-device FLOPs of the compiled SPMD program."""
        return _cost.executable_flops(self._executable(*batch))

    def _executable(self, *batch):
        sig, args = self._prep(batch, self._step_i + 1)
        entry = self._exec.get(sig)
        if entry is None:
            entry = self._warm_submit(sig, args, len(batch),
                                      inline=True).result()
        return entry[0]

    def sync_to_model(self):
        named = dict(self.model.named_parameters())
        with no_grad():
            for k, v in self.params.items():
                named[k]._slot = _Slot(v)
        if self.scaler is not None and self.scaler_state:
            self.scaler.sync_from_jit_state(self.scaler_state)

    def compiled_text(self, *batch):
        """Optimized HLO for inspection/tests; reuses the AOT executable
        cache — no extra compile once the step has run."""
        return self._executable(*batch).as_text()
