"""LocalSGD — K local optimizer steps per worker, then parameter averaging.

Parity: python/paddle/distributed/fleet/meta_optimizers/localsgd_optimizer.py
(LocalSGDOptimizer: workers train independently for k_steps, then
broadcast-average parameters). TPU-native design: instead of per-worker
processes + allreduce ops inserted into a Program, the per-worker replicas
live as a leading 'dp' axis on every parameter array, sharded over the dp
mesh axis. One jitted shard_map program runs the local step WITHOUT any
gradient psum (each device updates its own replica on its own batch
shard); every k-th call a pmean over 'dp' averages parameters AND
optimizer state (post-local-SGD-style momentum averaging) back into sync.

The payoff on TPU is the same as the reference's on GPU clusters: k-1 of
every k steps run with ZERO cross-device traffic — useful when the
interconnect (DCN between pods) is the bottleneck, not ICI.
"""
import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ...framework.jax_compat import shard_map

from ...framework.core import Tensor, no_grad, _Slot
from ...framework.random import split_key
from ...jit.api import functional_call, state_arrays

__all__ = ["LocalSGDTrainStep"]


class LocalSGDTrainStep:
    """Build once, call per batch; parameters sync every `k_steps` calls.

        step = LocalSGDTrainStep(model, loss_fn, opt, mesh, k_steps=4)
        for x, y in loader:
            loss = step(x, y)     # psum-free except on sync steps
    """

    def __init__(self, model, loss_fn, optimizer, mesh, k_steps=4,
                 begin_step=1, donate=True):
        if "dp" not in mesh.shape:
            raise ValueError("LocalSGD needs a 'dp' axis on the mesh")
        self.model = model
        self.optimizer = optimizer
        self.mesh = mesh
        self.k_steps = int(k_steps)
        # reference localsgd_configs['begin_step']: train synchronously
        # (sync every call) for the first begin_step calls, THEN switch
        # to K-local-steps mode
        self.begin_step = int(begin_step)
        self._call_i = 0
        dp = mesh.shape["dp"]
        self._dp = dp

        params, self.buffers = state_arrays(model)
        # one replica per dp rank, leading axis sharded over 'dp'
        rep = NamedSharding(mesh, P("dp"))
        self.params = {
            k: jax.device_put(jnp.broadcast_to(v[None], (dp,) + v.shape),
                              rep)
            for k, v in params.items()}
        self.opt_state = {
            k: jax.tree.map(
                lambda s: jax.device_put(
                    jnp.broadcast_to(s[None], (dp,) + s.shape), rep),
                optimizer.init_leaf_state(v))
            for k, v in params.items()}

        model_ref = model
        opt = optimizer

        def loss_of(ps, bufs, key, batch):
            from ...jit.api import reset_aux_losses, collect_aux_losses
            reset_aux_losses(model_ref)
            out = functional_call(model_ref, ps, bufs, batch[:-1],
                                  rng_key=key, training=True)
            l = loss_fn(out if isinstance(out, Tensor) else Tensor(out),
                        Tensor(batch[-1]))
            l = l.value if isinstance(l, Tensor) else l
            aux = collect_aux_losses(model_ref)
            return l if aux is None else l + aux.astype(l.dtype)

        from ...nn.clip import clip_grads_tree

        def _clip(grads):
            return clip_grads_tree(grads, opt._grad_clip)

        def make_local_step(sync):
            # `sync` is STATIC: the k-1 local-step program contains no
            # collective at all (the point of LocalSGD); the sync-step
            # program appends ONE pmean over params+state
            def local_step(params_, opt_state_, bufs, key, lr, step_i,
                           *batch):
                # inside shard_map: arrays are the PER-DEVICE block —
                # params carry their replica axis of size 1; drop it
                ps = jax.tree.map(lambda a: a[0], params_)
                st = jax.tree.map(lambda a: a[0], opt_state_)
                loss, grads = jax.value_and_grad(
                    lambda p: loss_of(p, bufs, key, batch))(ps)
                grads = _clip(grads)
                new_ps, new_st = opt.apply_gradients_tree(
                    ps, grads, st, lr, step_i)
                if sync:
                    new_ps = jax.tree.map(
                        lambda a: jax.lax.pmean(a, "dp"), new_ps)
                    new_st = jax.tree.map(
                        lambda a: jax.lax.pmean(a, "dp"), new_st)
                # loss stays per-replica (shape [1] per shard): averaging
                # happens on host, so local steps carry NO collective
                return (loss[None],
                        jax.tree.map(lambda a: a[None], new_ps),
                        jax.tree.map(lambda a: a[None], new_st))
            return local_step

        self._make_local_step = make_local_step
        self._donate = donate
        self._jit_cache = {}  # (n_batch_arrays, sync) -> jitted program

    def _build(self, n_batch, sync):
        rep_spec = jax.tree.map(lambda _: P("dp"), self.params)
        st_spec = jax.tree.map(lambda _: P("dp"), self.opt_state)
        smapped = shard_map(
            self._make_local_step(sync), mesh=self.mesh,
            in_specs=(rep_spec, st_spec, P(), P(), P(), P())
            + tuple(P("dp") for _ in range(n_batch)),
            out_specs=(P("dp"), rep_spec, st_spec),
            check_vma=False)
        return jax.jit(smapped,
                       donate_argnums=(0, 1) if self._donate else ())

    def __call__(self, *batch):
        arrays = [b.value if isinstance(b, Tensor) else jnp.asarray(b)
                  for b in batch]
        self._call_i += 1
        sync = bool(self._call_i <= self.begin_step
                    or self._call_i % self.k_steps == 0)
        key = (len(arrays), sync)
        jitted = self._jit_cache.get(key)
        if jitted is None:
            jitted = self._jit_cache[key] = self._build(*key)
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        batch_sh = NamedSharding(self.mesh, P("dp"))
        arrays = [jax.device_put(a, batch_sh) for a in arrays]
        losses, self.params, self.opt_state = jitted(
            self.params, self.opt_state, self.buffers, split_key(), lr,
            jnp.asarray(self._call_i, jnp.float32), *arrays)
        return Tensor(jnp.mean(losses))  # host-side mean over replicas

    def replica_spread(self):
        """Max abs deviation across replicas (0 right after a sync step) —
        observability for tests and drift monitoring."""
        m = 0.0
        for v in self.params.values():
            arr = np.asarray(v)
            m = max(m, float(np.max(np.abs(arr - arr[:1]))))
        return m

    def sync_to_model(self):
        """Average replicas into the eager model's parameters."""
        named = dict(self.model.named_parameters())
        with no_grad():
            for k, v in self.params.items():
                named[k]._slot = _Slot(jnp.mean(v, axis=0))
