"""Pipeline-parallel execution engine.

Parity: python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py
(PipelineParallel: 1F1B/GPipe schedules over NCCL p2p).

TPU-native design: the schedule is ONE SPMD program. Per-stage parameter
pytrees are stacked on a leading [pp] axis and sharded over the 'pp' mesh
axis; inside shard_map every device runs the same stage function on its
local shard while lax.ppermute rotates microbatch activations to the next
stage over ICI. The fill/steady/drain phases of GPipe fall out of a single
fori_loop of length (n_micro + n_stages - 1); reverse-mode AD through
ppermute yields the backward pipeline automatically, so 1F1B-style
interleaving is XLA's scheduling problem, not hand-written control flow
(see PAPERS.md: MPMD pipeline parallelism — we deliberately choose the
SPMD formulation natural to XLA).

Constraint (documented): stages must be structurally uniform (same layer
stack per stage) — embedding/head run replicated outside the pipelined
segment. This matches how transformer trunks are pipelined in practice.
"""
import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from jax import shard_map

from ...framework.core import Tensor
from ...jit.api import functional_call, state_arrays

__all__ = ["PipelineParallel", "pipeline_apply"]


def pipeline_apply(stage_fn, stacked_params, x_micro, mesh, n_stages,
                   n_micro):
    """Run the GPipe schedule. stacked_params leaves: [pp, ...];
    x_micro: [n_micro, mb, ...] (replicated over pp). Returns stacked
    last-stage outputs [n_micro, mb, ...]."""

    def spmd(params_local, xs):
        # params_local leaves: [1, ...] → this stage's params
        params_here = jax.tree.map(lambda a: a[0], params_local)
        stage = jax.lax.axis_index("pp")
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        T = n_micro + n_stages - 1
        mb_shape = xs.shape[1:]
        outputs = jnp.zeros((n_micro,) + mb_shape, xs.dtype)
        carry = jnp.zeros(mb_shape, xs.dtype)

        def tick(t, state):
            recv, outputs = state
            feed_idx = jnp.clip(t, 0, n_micro - 1)
            first_in = jnp.where(t < n_micro, xs[feed_idx],
                                 jnp.zeros(mb_shape, xs.dtype))
            inp = jnp.where(stage == 0, first_in, recv)
            out = stage_fn(params_here, inp)
            out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
            is_valid = (t >= n_stages - 1) & (stage == n_stages - 1)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs,
                jnp.where(is_valid, out, outputs[out_idx]), out_idx, 0)
            recv = jax.lax.ppermute(out, "pp", perm)
            return recv, outputs

        recv, outputs = jax.lax.fori_loop(0, T, tick, (carry, outputs))
        # broadcast last-stage outputs to every pp rank so downstream
        # (replicated head/loss) sees them
        outputs = jax.lax.psum(
            jnp.where(stage == n_stages - 1, outputs, 0.0), "pp")
        return outputs

    pp_specs = jax.tree.map(lambda _: P("pp"), stacked_params)
    return shard_map(
        spmd, mesh=mesh,
        in_specs=(pp_specs, P()), out_specs=P(),
        check_vma=False)(stacked_params, x_micro)


class PipelineParallel:
    """Engine over a PipelineLayer: builds the stacked-stage params and a
    jitted train step. Used by fleet and by tests/dryrun."""

    def __init__(self, pipeline_layer, optimizer, mesh, n_micro=2,
                 loss_fn=None):
        self.layer = pipeline_layer
        self.optimizer = optimizer
        self.mesh = mesh
        self.n_micro = n_micro
        self.n_stages = pipeline_layer.num_stages
        self.loss_fn = loss_fn or pipeline_layer._loss_fn
        self._step_i = 0

        # build stacked per-stage params; stages must be uniform
        seg_params = []
        for seg in pipeline_layer.segments:
            stage_arrays = {}
            for idx, (layer, tag) in enumerate(seg):
                if tag == "fn" or not hasattr(layer, "named_parameters"):
                    continue
                for name, p in layer.named_parameters():
                    stage_arrays[f"{idx}.{name}"] = p.value
            seg_params.append(stage_arrays)
        keys = sorted(seg_params[0].keys())
        for sp in seg_params[1:]:
            if sorted(sp.keys()) != keys:
                raise ValueError(
                    "pipeline stages are not structurally uniform: "
                    f"{sorted(sp.keys())} vs {keys}")
        self.stacked = {
            k: jnp.stack([sp[k] for sp in seg_params]) for k in keys}
        pp_shard = {k: NamedSharding(mesh, P("pp"))
                    for k in self.stacked}
        self.stacked = {k: jax.device_put(v, pp_shard[k])
                        for k, v in self.stacked.items()}
        self.opt_state = {
            k: tuple(jax.device_put(s, pp_shard[k])
                     for s in optimizer._init_state(v))
            for k, v in self.stacked.items()}

        seg0 = pipeline_layer.segments[0]
        layers0 = [l for l, tag in seg0 if hasattr(l, "named_parameters")]

        def stage_fn(params_here, x):
            out = x
            for idx, (layer, tag) in enumerate(seg0):
                if tag == "fn":
                    out = layer(Tensor(out)).value if isinstance(
                        out, jnp.ndarray) else layer(out)
                    continue
                prefix = f"{idx}."
                sub = {name[len(prefix):]: arr
                       for name, arr in params_here.items()
                       if name.startswith(prefix)}
                out = functional_call(layer, sub, {}, (out,),
                                      training=True)
            return out

        self._stage_fn = stage_fn
        mesh_ = mesh
        n_stages = self.n_stages
        n_micro_ = n_micro
        opt = optimizer
        lfn = self.loss_fn

        def train_step(stacked, opt_state, lr, step_i, x, y):
            xm = jnp.stack(jnp.split(x, n_micro_, axis=0))

            def loss_of(ps):
                outs = pipeline_apply(stage_fn, ps, xm, mesh_, n_stages,
                                      n_micro_)
                flat = outs.reshape((-1,) + outs.shape[2:])
                l = lfn(Tensor(flat), Tensor(y))
                return l.value if isinstance(l, Tensor) else l

            loss, grads = jax.value_and_grad(loss_of)(stacked)
            new_p, new_s = opt.apply_gradients_tree(stacked, grads,
                                                    opt_state, lr, step_i)
            return loss, new_p, new_s

        self._jitted = jax.jit(train_step, donate_argnums=(0, 1))

    def train_batch(self, x, y):
        self._step_i += 1
        xa = x.value if isinstance(x, Tensor) else jnp.asarray(x)
        ya = y.value if isinstance(y, Tensor) else jnp.asarray(y)
        loss, self.stacked, self.opt_state = self._jitted(
            self.stacked, self.opt_state,
            jnp.asarray(self.optimizer.get_lr(), jnp.float32),
            self._step_i, xa, ya)
        return Tensor(loss)

    def forward(self, x):
        xa = x.value if isinstance(x, Tensor) else jnp.asarray(x)
        xm = jnp.stack(jnp.split(xa, self.n_micro, axis=0))
        outs = pipeline_apply(self._stage_fn, self.stacked, xm, self.mesh,
                              self.n_stages, self.n_micro)
        return Tensor(outs.reshape((-1,) + outs.shape[2:]))
