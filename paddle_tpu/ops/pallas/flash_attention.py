"""Fused flash-attention TRAINING kernel for TPU in Pallas.

The training-side twin of paged_attention.py: the SAME blocking policy
and online-softmax block update (ops/pallas/attention_core.py owns
both) applied to the contiguous case — q-blocks of one sequence's
tokens against kv blocks of the same sequence, so the [T, T]
probability matrix never materializes in HBM. Block shapes come from
attention_core.choose_flash_blocks (VMEM-budget-capped, measured on
real TPU); every score dot is [bq, D] x [D, bk] with bq targeting the
same MXU tiles the serving kernel's q-block/head folding targets, and
tools/check_dot_shapes.py ratchets both kernels against the same M >= 8
floor.

Backward is the standard two-pass flash backward (dq pass, then dk/dv
pass) via jax.custom_vjp, recomputing probabilities from the saved lse
and accumulating in f32 scratch.

Layout contract: q, k, v are [batch, seq, heads, head_dim] (the
framework's fused-attention layout); internally folded to [B*H, T, D].
Causal masking is attention_core.causal_valid per block; blocks
strictly above the diagonal are skipped outright.
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .common import I0, NEG_INF  # noqa: F401
from . import attention_core as core


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref,
                l_ref, *, scale, causal, block_q, block_k):
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        m0, l0, acc0 = core.softmax_carry(block_q, q_ref.shape[-1])
        m_ref[:], l_ref[:], acc_ref[:] = m0, l0, acc0

    def _body():
        q = q_ref[0].astype(jnp.float32)          # [bq, d]
        k = k_ref[0].astype(jnp.float32)          # [bk, d]
        v = v_ref[0].astype(jnp.float32)          # [bk, d]
        s = core.score_dot(q, k, scale)           # [bq, bk]
        valid = (core.causal_valid(iq, ik, block_q, block_k)
                 if causal else None)
        m_ref[:], l_ref[:], acc_ref[:] = core.softmax_update(
            m_ref[:], l_ref[:], acc_ref[:], s, v, valid=valid)

    if causal:
        # skip blocks strictly above the diagonal band
        @pl.when(ik * block_k <= (iq + 1) * block_q - 1)
        def _run():
            _body()
    else:
        _body()

    @pl.when(ik == nk - 1)
    def _finalize():
        out, lse = core.softmax_finalize(m_ref[:], l_ref[:], acc_ref[:])
        o_ref[0] = out.astype(o_ref.dtype)
        lse_ref[0, 0] = lse


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_acc, *, scale, causal, block_q, block_k):
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def _body():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        s = core.score_dot(q, k, scale)
        if causal:
            s = jnp.where(core.causal_valid(iq, ik, block_q, block_k),
                          s, jnp.float32(NEG_INF))
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * jnp.float32(scale)
        dq_acc[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(ik * block_k <= (iq + 1) * block_q - 1)
        def _run():
            _body()
    else:
        _body()

    @pl.when(ik == nk - 1)
    def _fin():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref,
                dv_ref, dk_acc, dv_acc, *, scale, causal, block_q,
                block_k):
    ik = pl.program_id(1)
    iq = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _body():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        s = core.score_dot(q, k, scale)
        if causal:
            s = jnp.where(core.causal_valid(iq, ik, block_q, block_k),
                          s, jnp.float32(NEG_INF))
        p = jnp.exp(s - lse[:, None])                       # [bq, bk]
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # [bk, d]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * jnp.float32(scale)  # [bq, bk]
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)              # [bk, d]

    if causal:
        @pl.when(ik * block_k <= (iq + 1) * block_q - 1)
        def _run():
            _body()
    else:
        _body()

    @pl.when(iq == nq - 1)
    def _fin():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, scale, interpret):
    out, _ = _flash_fwd_impl(q, k, v, causal, scale, interpret)
    return out


def _flash_fwd_impl(q, k, v, causal, scale, interpret):
    BH, Tq, D = q.shape
    Tk = k.shape[1]
    bq, bk = core.choose_flash_blocks(Tq, Tk, D)
    grid = (BH, Tq // bq, Tk // bk)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, I0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, I0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, I0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, I0)),
            pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, I0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Tq, D), q.dtype),
            # lse kept [BH, 1, Tq]: trailing block dims (1, bq) satisfy the
            # TPU (8, 128) tiling rule, which a [BH, Tq] layout cannot
            jax.ShapeDtypeStruct((BH, 1, Tq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),
            pltpu.VMEM((bq,), jnp.float32),
            pltpu.VMEM((bq,), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse


def _flash_fwd(q, k, v, causal, scale, interpret):
    out, lse = _flash_fwd_impl(q, k, v, causal, scale, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, interpret, res, dout):
    q, k, v, out, lse = res
    BH, Tq, D = q.shape
    Tk = k.shape[1]
    bq, bk = core.choose_flash_blocks(Tq, Tk, D)
    delta = jnp.sum(out.astype(jnp.float32) * dout.astype(jnp.float32),
                    axis=-1)[:, None, :]  # [BH, 1, Tq]

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk),
        grid=(BH, Tq // bq, Tk // bk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, I0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, I0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, I0)),
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, I0)),
            pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, I0, i)),
            pl.BlockSpec((1, 1, bq), lambda b, i, j: (b, I0, i)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, I0)),
        out_shape=jax.ShapeDtypeStruct((BH, Tq, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
    )(q, k, v, dout, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk),
        grid=(BH, Tk // bk, Tq // bq),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, I0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, I0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, I0)),
            pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, I0)),
            pl.BlockSpec((1, 1, bq), lambda b, j, i: (b, I0, i)),
            pl.BlockSpec((1, 1, bq), lambda b, j, i: (b, I0, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, I0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, I0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Tk, D), k.dtype),
            jax.ShapeDtypeStruct((BH, Tk, D), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)],
        interpret=interpret,
    )(q, k, v, dout, lse, delta)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention_arrays(q, k, v, causal=False, scale=None,
                           interpret=False):
    """Array-level entry: q,k,v [B, T, H, D] → out [B, T, H, D]."""
    B, Tq, H, D = q.shape
    scale = core.default_scale(scale, D)
    fold = lambda x: jnp.swapaxes(x, 1, 2).reshape(B * H, x.shape[1], D)
    out = _flash(fold(q), fold(k), fold(v), causal, scale, interpret)
    return jnp.swapaxes(out.reshape(B, H, Tq, D), 1, 2)


def flash_attention(q, k, v, causal=False, scale=None, interpret=None):
    """Tensor-level entry used by F.scaled_dot_product_attention."""
    from ...framework.core import apply_op
    interpret = core.default_interpret(interpret)
    return apply_op(
        lambda qa, ka, va: flash_attention_arrays(
            qa, ka, va, causal=causal, scale=scale, interpret=interpret),
        q, k, v)
