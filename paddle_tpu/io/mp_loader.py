"""Multiprocess DataLoader workers.

Parity: python/paddle/fluid/dataloader/dataloader_iter.py:326
(_DataLoaderIterMultiProcess) — subprocess workers so CPU-bound python
transforms actually scale past the GIL (the threaded path can't).

Design:
- spawn context (fork would duplicate an initialized TPU/jax runtime);
- the dataset/collate_fn travel as pickle blobs and are unpickled INSIDE
  the worker after its env is pinned to the CPU jax backend, so worker
  code can never touch the TPU tunnel;
- workers return NUMPY trees; the parent converts leaves to Tensors
  (device put happens once, in the parent, next to the consumer);
- an index queue feeds (batch_id, indices); a reorder buffer on the
  parent restores deterministic batch order (reference semantics);
- persistent_workers keeps the pool across epochs.

Falls back to the threaded ring-buffer path when the dataset or
collate_fn cannot be pickled (the caller handles that).
"""
import os
import pickle
import queue
import traceback

import numpy as np

_SENTINEL = None


def _np_collate(batch):
    """default_collate over numpy — no jax/Tensor in the workers."""
    sample = batch[0]
    tname = type(sample).__name__
    if tname == "Tensor":  # dataset made Tensors (cpu jax) — detach to np
        return np.stack([np.asarray(s.numpy()) for s in batch])
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, dict):
        return {k: _np_collate([s[k] for s in batch]) for k in sample}
    if isinstance(sample, (tuple, list)):
        return [_np_collate([s[i] for s in batch])
                for i in range(len(sample))]
    return batch


def _np_detach(tree):
    """Tensors (weakref-bearing, unpicklable) → numpy before the queue."""
    if type(tree).__name__ == "Tensor":
        return np.asarray(tree.numpy())
    if hasattr(tree, "dtype") and hasattr(tree, "__array__") and \
            not isinstance(tree, np.ndarray):
        return np.asarray(tree)  # jax arrays etc.
    if isinstance(tree, dict):
        return {k: _np_detach(v) for k, v in tree.items()}
    if isinstance(tree, tuple):
        return tuple(_np_detach(v) for v in tree)
    if isinstance(tree, list):
        return [_np_detach(v) for v in tree]
    return tree


def _worker_loop(dataset_blob, collate_blob, init_blob, index_q, result_q,
                 wid, num_workers):
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    os.environ.pop("PALLAS_AXON_REMOTE_COMPILE", None)
    try:
        dataset = pickle.loads(dataset_blob)
        collate = pickle.loads(collate_blob)
        init_fn = pickle.loads(init_blob)
        if init_fn is not None:
            init_fn(wid)
        try:
            from . import _worker_info, WorkerInfo
            _worker_info.info = WorkerInfo(wid, num_workers, dataset)
        except Exception:
            pass
    except Exception:
        result_q.put((-1, None, traceback.format_exc()))
        return
    while True:
        item = index_q.get()
        if item is _SENTINEL:
            return
        bid, indices = item
        try:
            samples = [dataset[i] for i in indices]
            batch = collate(samples) if collate is not None \
                else _np_collate(samples)
            result_q.put((bid, _np_detach(batch), None))
        except Exception:
            result_q.put((bid, None, traceback.format_exc()))


class MultiprocessPool:
    """A spawn-context worker pool + ordered batch iterator."""

    def __init__(self, dataset, collate_fn, num_workers, worker_init_fn,
                 prefetch_factor=2):
        import multiprocessing as mp
        ctx = mp.get_context("spawn")
        # pickle up front: raises immediately if not transportable
        self._blobs = (pickle.dumps(dataset), pickle.dumps(collate_fn),
                       pickle.dumps(worker_init_fn))
        self.num_workers = num_workers
        self.prefetch = max(1, prefetch_factor) * num_workers
        self._index_q = ctx.Queue()
        self._result_q = ctx.Queue()
        self._procs = [
            ctx.Process(target=_worker_loop,
                        args=(*self._blobs, self._index_q, self._result_q,
                              i, num_workers),
                        daemon=True)
            for i in range(num_workers)]
        for p in self._procs:
            p.start()
        self._alive = True

    def run_epoch(self, index_iter, timeout):
        """Yield collated numpy batches in sampler order."""
        if not self._alive:
            raise RuntimeError("worker pool already shut down")
        pending = {}
        next_out = 0
        next_in = 0
        exhausted = False
        index_iter = iter(index_iter)
        inflight = 0
        while True:
            while not exhausted and inflight < self.prefetch:
                try:
                    indices = next(index_iter)
                except StopIteration:
                    exhausted = True
                    break
                self._index_q.put((next_in, list(indices)))
                next_in += 1
                inflight += 1
            if exhausted and inflight == 0:
                return
            try:
                bid, batch, err = self._result_q.get(
                    timeout=timeout if timeout else None)
            except queue.Empty:
                self.shutdown()
                raise RuntimeError(
                    f"DataLoader worker timed out after {timeout}s")
            if err is not None:
                self.shutdown()
                raise RuntimeError(f"DataLoader worker failed:\n{err}")
            inflight -= 1
            pending[bid] = batch
            while next_out in pending:
                yield pending.pop(next_out)
                next_out += 1

    def shutdown(self):
        if not self._alive:
            return
        self._alive = False
        for _ in self._procs:
            try:
                self._index_q.put(_SENTINEL)
            except Exception:
                pass
        for p in self._procs:
            p.join(timeout=2)
            if p.is_alive():
                p.terminate()

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass
