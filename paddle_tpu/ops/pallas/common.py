"""Shared constants for the Pallas TPU kernels.

The package runs with jax_enable_x64=True (paddle exposes float64/int64
dtypes), which makes bare Python literals trace as i64/f64 — types Mosaic
cannot legalize inside kernels or index maps. Kernels therefore use these
pre-typed constants (and wrap every float closure scalar in jnp.float32).
"""
import numpy as np

# i32 index-map constant (x64 mode would make a literal 0 trace as i64)
I0 = np.int32(0)

# additive mask value; finite so exp() underflows cleanly instead of NaN
NEG_INF = -1e30
