"""paddle.device.cuda shim mapping onto the TPU runtime.
Parity: python/paddle/device/cuda/__init__.py — importable as a real
submodule so `from paddle.device.cuda import synchronize` works."""
from . import Stream, Event  # noqa: F401
from . import synchronize as _synchronize, _default_device

__all__ = ["Stream", "Event", "device_count", "synchronize",
           "max_memory_allocated", "memory_allocated", "empty_cache"]


def device_count():
    return 0


def synchronize(device=None):
    _synchronize()


def max_memory_allocated(device=None):
    # single source of truth with paddle.device.max_memory_allocated
    # (memory_stats() returns None on backends without allocator stats —
    # the parent module handles that and the RSS fallback)
    from . import max_memory_allocated as _impl
    return _impl(device)


def memory_allocated(device=None):
    from . import memory_allocated as _impl
    return _impl(device)


def empty_cache():
    pass
