"""paddle.tensor.array — TensorArray ops.

Parity: /root/reference/python/paddle/tensor/array.py. In the
reference, dynamic mode backs the array with a Python list and static
mode with a LOD_TENSOR_ARRAY variable; here the list representation is
used everywhere — under trace (jit.to_static / static.Program capture)
a list of traced values stages cleanly into the jaxpr, so no separate
variable kind is needed.
"""
import numpy as np

from ..framework.core import Tensor

__all__ = []


def _index(i):
    """Positional index as a host int (write positions are trace-time
    constants in the list representation, as in reference dygraph)."""
    if isinstance(i, Tensor):
        return int(np.asarray(i.numpy()).reshape(-1)[0])
    if hasattr(i, "shape") and getattr(i, "shape", None):
        return int(np.asarray(i).reshape(-1)[0])
    return int(i)


def array_length(array):
    """Length of the array as a 1-D int64 Tensor of shape [1]."""
    return Tensor(np.asarray([len(array)], np.int64))


def array_read(array, i):
    """Read the element at position ``i``."""
    return array[_index(i)]


def array_write(x, i, array=None):
    """Write ``x`` at position ``i``; the array auto-grows to position
    ``i`` when the subscript is past the end, matching the reference's
    ``write_to_array`` op whose own docstring writes at subscript 10 of
    a fresh array (reference fluid/layers/control_flow.py:1479 — the
    result is "a LoDTensorArray with length 11"). Gap slots are filled
    with ZEROS of the written tensor's shape and dtype — the reference
    leaves them uninitialized, but a 0-length filler makes stack/concat
    over the array blow up far from the write site with a shape error
    that names no culprit. Returns the (possibly new) array."""
    if array is None:
        array = []
    idx = _index(i)
    if idx < 0:
        raise IndexError(f"array_write position {idx} is negative")
    if idx > len(array):
        # one zero buffer shared (immutably) by every gap slot — a
        # per-slot allocation would cost gap_count * sizeof(x)
        fill = _zeros_like_written(x)
        while idx > len(array):
            array.append(Tensor(fill.value))
    if idx == len(array):
        array.append(x)
    else:
        array[idx] = x
    return array


def _zeros_like_written(x):
    """A zero filler matching ``x``'s shape and dtype. Goes through the
    value's own jax dtype — np.dtype(str(...)) mangles bfloat16 (numpy
    has no such dtype; str round-trips produced float32 fillers that
    poisoned later concat/stack dtype promotion)."""
    import jax.numpy as jnp

    if isinstance(x, Tensor):
        return Tensor(jnp.zeros(tuple(x.shape), x.value.dtype))
    arr = np.asarray(x)
    return Tensor(np.zeros(arr.shape, arr.dtype))


def create_array(dtype, initialized_list=None):
    """A new TensorArray (Python list), optionally pre-filled."""
    array = []
    if initialized_list is not None:
        if not isinstance(initialized_list, (list, tuple)):
            raise TypeError(
                "initialized_list should be a list of Tensors, got "
                f"{type(initialized_list)}")
        array = list(initialized_list)
    for val in array:
        if not isinstance(val, Tensor):
            raise TypeError(
                "All values in `initialized_list` should be Tensors, "
                f"got {type(val)}")
    return array
