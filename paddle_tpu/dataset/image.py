"""paddle.dataset.image — cv2-backed image utilities for the legacy
reader pipelines.

Parity: /root/reference/python/paddle/dataset/image.py (HWC uint8
in-memory format, CHW conversion at the end of the pipeline).
"""
import tarfile

import numpy as np

try:
    import cv2
except ImportError:  # pragma: no cover - cv2 is baked into this image
    cv2 = None

__all__ = []


def _check_cv2():
    if cv2 is None:
        raise ImportError(
            "opencv-python is required for paddle.dataset.image")
    return True


def batch_images_from_tar(data_file, dataset_name, img2label,
                          num_per_batch=1024):
    """Pickle (image bytes, label) samples from a tar into batch files
    next to the tar; returns the meta-file path."""
    import pickle
    import os
    batch_dir = data_file + "_batch"
    out_path = f"{batch_dir}/{dataset_name}"
    meta_file = f"{batch_dir}/{dataset_name}_batch_master.txt"
    if os.path.exists(out_path):
        return meta_file
    os.makedirs(out_path, exist_ok=True)
    tf = tarfile.open(data_file)
    mems = tf.getmembers()
    data, labels = [], []
    file_id = 0
    names = []
    for mem in mems:
        if mem.name in img2label:
            data.append(tf.extractfile(mem).read())
            labels.append(img2label[mem.name])
            if len(data) == num_per_batch:
                output = {"label": labels, "data": data}
                name = f"{out_path}/batch_{file_id}"
                with open(name, "wb") as f:
                    pickle.dump(output, f, protocol=2)
                names.append(name)
                file_id += 1
                data, labels = [], []
    if data:
        output = {"label": labels, "data": data}
        name = f"{out_path}/batch_{file_id}"
        with open(name, "wb") as f:
            pickle.dump(output, f, protocol=2)
        names.append(name)
    with open(meta_file, "a") as meta:
        for name in names:
            meta.write(os.path.abspath(name) + "\n")
    return meta_file


def load_image_bytes(bytes_, is_color=True):
    """Decode an encoded image buffer to an HWC (or HW) uint8 array."""
    _check_cv2()
    flag = cv2.IMREAD_COLOR if is_color else cv2.IMREAD_GRAYSCALE
    buf = np.frombuffer(bytes_, dtype="uint8")
    return cv2.imdecode(buf, flag)


def load_image(file, is_color=True):
    _check_cv2()
    flag = cv2.IMREAD_COLOR if is_color else cv2.IMREAD_GRAYSCALE
    return cv2.imread(file, flag)


def resize_short(im, size):
    """Resize so the shorter edge becomes `size` (aspect preserved)."""
    _check_cv2()
    h, w = im.shape[:2]
    if h > w:
        h_new, w_new = size * h // w, size
    else:
        h_new, w_new = size, size * w // h
    return cv2.resize(im, (w_new, h_new),
                      interpolation=cv2.INTER_CUBIC)


def to_chw(im, order=(2, 0, 1)):
    assert len(im.shape) == len(order)
    return im.transpose(order)


def center_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    h_start = (h - size) // 2
    w_start = (w - size) // 2
    h_end, w_end = h_start + size, w_start + size
    if is_color:
        return im[h_start:h_end, w_start:w_end, :]
    return im[h_start:h_end, w_start:w_end]


def random_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    h_start = np.random.randint(0, h - size + 1)
    w_start = np.random.randint(0, w - size + 1)
    h_end, w_end = h_start + size, w_start + size
    if is_color:
        return im[h_start:h_end, w_start:w_end, :]
    return im[h_start:h_end, w_start:w_end]


def left_right_flip(im, is_color=True):
    if len(im.shape) == 3 and is_color:
        return im[:, ::-1, :]
    return im[:, ::-1]


def simple_transform(im, resize_size, crop_size, is_train,
                     is_color=True, mean=None):
    """resize_short → crop (random + flip when training) → CHW float32
    → optional mean subtraction."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, is_color=is_color)
        if np.random.randint(2) == 0:
            im = left_right_flip(im, is_color)
    else:
        im = center_crop(im, crop_size, is_color=is_color)
    if len(im.shape) == 3:
        im = to_chw(im)
    im = im.astype("float32")
    if mean is not None:
        mean = np.array(mean, dtype=np.float32)
        if mean.ndim == 1 and is_color:
            mean = mean[:, np.newaxis, np.newaxis]
        elif mean.ndim == 1:
            mean = mean
        im -= mean
    return im


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    im = load_image(filename, is_color)
    return simple_transform(im, resize_size, crop_size, is_train,
                            is_color, mean)
