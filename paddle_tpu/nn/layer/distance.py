"""Distance layers. Parity: python/paddle/nn/layer/distance.py."""
import jax.numpy as jnp

from ...framework.core import apply_op
from .layers import Layer

__all__ = ["PairwiseDistance"]


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p = p
        self.epsilon = epsilon
        self.keepdim = keepdim

    def forward(self, x, y):
        p, eps, keep = self.p, self.epsilon, self.keepdim

        def fn(a, b):
            d = a - b + eps
            return jnp.sum(jnp.abs(d) ** p, axis=-1,
                           keepdims=keep) ** (1.0 / p)
        return apply_op(fn, x, y)
