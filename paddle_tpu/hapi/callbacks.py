"""Training callbacks. Parity: python/paddle/hapi/callbacks.py."""
import numbers
import os
import time

import numpy as np

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "EarlyStopping",
           "LRScheduler", "ReduceLROnPlateau", "VisualDL",
           "config_callbacks"]


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = callbacks

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def _call(self, name, *args):
        for c in self.callbacks:
            getattr(c, name)(*args)

    def on_begin(self, mode, logs=None):
        self._call(f"on_{mode}_begin", logs or {})

    def on_end(self, mode, logs=None):
        self._call(f"on_{mode}_end", logs or {})

    def on_epoch_begin(self, epoch, logs=None):
        self._call("on_epoch_begin", epoch, logs or {})

    def on_epoch_end(self, epoch, logs=None):
        self._call("on_epoch_end", epoch, logs or {})

    def on_batch_begin(self, mode, step, logs=None):
        self._call(f"on_{mode}_batch_begin", step, logs or {})

    def on_batch_end(self, mode, step, logs=None):
        self._call(f"on_{mode}_batch_end", step, logs or {})


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = 0
        self._t0 = time.time()
        self._tb = time.time()

    def on_train_batch_end(self, step, logs=None):
        from ..profiler import monitor as _monitor
        now = time.time()
        dt = now - self._tb
        self._tb = now
        _monitor.histogram("hapi.step_s").observe(dt)
        self.steps += 1
        if self.verbose:
            # training-health anomalies (Model.prepare(monitor_health=
            # True)): rare, so always worth a line when they fire
            for ev in (logs or {}).get("anomalies", ()):
                detail = {k: v for k, v in ev.items()
                          if k not in ("event", "step")}
                print(f"[health] step {ev.get('step', step)}: "
                      f"{ev.get('event')} {detail}")
        if self.verbose and step % self.log_freq == 0:
            loss = logs.get("loss")
            # float() resolves a deferred loss handle — log_freq
            # boundaries are the fit loop's only mid-epoch host sync
            lstr = ", ".join(f"{float(v):.4f}" for v in loss) \
                if loss else "-"
            extra = f", {dt * 1000:.0f} ms/step"
            # cost-analysis MFU published by the jitted train steps
            # (jit/api.py export_step_metrics); eager fit() has no
            # compiled executable to account against
            mfu = _monitor.gauge("train.mfu").value
            if mfu:
                extra += f", mfu={mfu:.3f}"
            print(f"Epoch {self.epoch} step {step}: loss={lstr}{extra}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            print(f"Epoch {epoch} done in {dt:.1f}s {logs}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            self.model.save(os.path.join(self.save_dir, str(epoch)))

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "min" if "loss" in monitor or "err" in monitor else "max"
        self.mode = mode
        self.best = None
        self.wait = 0
        self.stopped_epoch = None  # set when training halts (ref parity)

    def _better(self, cur, best):
        if self.mode == "min":
            return cur < best - self.min_delta
        return cur > best + self.min_delta

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        value = logs.get(self.monitor, logs.get("eval_" + self.monitor))
        if value is None:
            return
        if isinstance(value, (list, tuple)):
            value = value[0] if value else None
        if value is None:
            return
        if self.best is None or self._better(value, self.best):
            self.best = value
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stopped_epoch = epoch
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s:
                s.step()


class ReduceLROnPlateau(Callback):
    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        self.monitor = monitor
        self.kwargs = dict(factor=factor, patience=patience,
                           cooldown=cooldown, min_lr=min_lr)

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        value = logs.get(self.monitor, logs.get("eval_" + self.monitor))
        if isinstance(value, (list, tuple)):
            value = value[0] if value else None
        opt = getattr(self.model, "_optimizer", None)
        sched = getattr(opt, "_learning_rate", None)
        if value is not None and hasattr(sched, "step") and \
                "Plateau" in type(sched).__name__:
            sched.step(metrics=value)


class VisualDL(Callback):
    """Scalar logging; writes a plain jsonl trace (visualdl package is not
    in the image — the format is trivially importable)."""

    def __init__(self, log_dir="./log"):
        super().__init__()
        self.log_dir = log_dir
        self._f = None
        self._step = 0
        self._pending = []

    def on_train_begin(self, logs=None):
        os.makedirs(self.log_dir, exist_ok=True)
        self._f = open(os.path.join(self.log_dir, "scalars.jsonl"), "a")

    def on_train_batch_end(self, step, logs=None):
        # hold the (deferred) loss handle — resolving here would block
        # the host on the step dispatched microseconds ago, undoing the
        # async loop; scalars flush at epoch/train end
        loss = (logs or {}).get("loss")
        if loss:
            self._pending.append((self._step, loss[0]))
        self._step += 1

    def _drain(self):
        import json
        if self._f:
            for s, v in self._pending:
                self._f.write(json.dumps(
                    {"step": s, "loss": float(v)}) + "\n")
            self._f.flush()
        self._pending = []

    def on_epoch_end(self, epoch, logs=None):
        self._drain()

    def on_train_end(self, logs=None):
        self._drain()
        if self._f:
            self._f.close()


def config_callbacks(callbacks, model, epochs, steps, verbose, log_freq,
                     save_dir, save_freq, metrics):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks.append(ProgBarLogger(log_freq, verbose=verbose))
    cl = CallbackList(cbks)
    cl.set_model(model)
    cl.set_params({"epochs": epochs, "steps": steps, "verbose": verbose,
                   "metrics": ["loss"] + [m.name() for m in metrics]})
    return cl
