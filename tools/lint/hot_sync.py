"""hot-sync pass: no host synchronization in the designated hot-loop
regions — `tools/check_no_hot_sync.py` migrated into the paddlelint
framework.

The async step pipeline (device prefetch ring, deferred loss handles,
scanned accumulation — docs/PERFORMANCE.md "Hiding the host") and the
serving scheduler only work while the steady-state loops never block
the host on the device. This pass is the regression fence: it fails
when a blocking read — `.item()`, `float(`, `.numpy()`,
`block_until_ready`, `np.asarray(`, `device_get(` — appears inside a
designated hot region.

The region table, patterns, allowlist marker (`# hot-sync-ok: <why>`)
and `check_source`/`check_repo` semantics are EXACTLY the historical
tool's — tools/check_no_hot_sync.py is now a thin shim over this
module, and its CLI stdout/exit behavior is unchanged (proven by the
pre-existing tests/test_async_pipeline.py lint tests running
untouched). The region table is documented in
docs/STATIC_ANALYSIS.md "Hot regions".

On top of the legacy semantics, the framework adds the ledger view:
allow-marked lines that DO match a sync pattern are emitted as
SUPPRESSED findings (the marker's <why> is the reason), so the
`kind:"lint"` JSONL and the baseline ratchet account for every
deliberate sync; a reasonless marker is flagged by the shared
suppression engine (core.apply_suppressions).
"""
import ast
import os
import re

from .core import Finding, HOT_SYNC_OK_RE, string_mask

PASS_NAME = "hot-sync"

HOT_REGIONS = {
    "paddle_tpu/jit/api.py": [
        "TrainStep.__call__", "TrainStep._prep", "TrainStep._dispatch",
        "TrainStep.accumulate", "TrainStep.run_steps",
        # the device-time probe (distributed observatory): its TWO
        # blocking reads are the measurement itself — cadence-gated
        # (PADDLE_TPU_DEVICE_TIME_EVERY) and explicitly hot-sync-ok
        # marked; fencing the functions keeps anything else out
        "device_probe_open", "device_probe_close",
        # the checkpoint snapshot hook: on-device buffer copies only —
        # the blocking device read belongs to the background writer
        # (distributed/checkpoint.py _write_one), never the step loop
        "CheckpointSnapshotMixin.tree_state",
        "CheckpointSnapshotMixin.snapshot_state"],
    "paddle_tpu/hapi/model.py": [
        "Model.fit", "Model._fit_epochs", "Model._dispatch_micro"],
    "paddle_tpu/distributed/fleet/hybrid_train.py": [
        "HybridTrainStep.__call__", "HybridTrainStep._prep"],
    # the async checkpoint enqueue path: save() snapshots on device and
    # hands off to the writer thread — any host<->device sync here
    # would put checkpointing back on the step loop's critical path.
    # (_write_one / the writer loop are deliberately NOT fenced: the
    # writer thread's whole job is the blocking device_get + file IO.)
    "paddle_tpu/distributed/checkpoint.py": [
        "CheckpointManager.save", "CheckpointManager._snapshot",
        "CheckpointManager.busy", "AsyncSaveHandle.done"],
    "paddle_tpu/distributed/elastic.py": [
        "ElasticController.on_step"],
    # fault sites fire inside train-step dispatch: pure host dict math
    "paddle_tpu/framework/fault_injection.py": ["fire", "active"],
    "paddle_tpu/io/device_prefetch.py": ["*"],
    # the serving engine's scheduler core: the only legitimate blocks
    # are the queue wait and the ONE device read per dispatched batch /
    # decode step (marked hot-sync-ok at the result-slicing sync
    # points). Sampling is an on-device argmax collected via an async
    # copy: the prefill path (_admit) and the whole ragged loop carry
    # NO allowlist entry — int()/device_get of b int32s with the copy
    # already in flight, never a [vocab]-sized np.asarray
    "paddle_tpu/inference/serving.py": [
        "_run_scheduler",
        "InferenceEngine._take_batch", "InferenceEngine._scan_matching",
        "InferenceEngine._loop_once", "InferenceEngine._dispatch_batch",
        "InferenceEngine._resolve_batch", "InferenceEngine._fail_batch",
        "InferenceEngine._flush_expired", "InferenceEngine.load_report",
        "GenerationEngine._loop_once", "GenerationEngine._admit",
        "GenerationEngine._decode_step", "GenerationEngine._emit",
        "GenerationEngine._admit_ragged",
        "GenerationEngine._ragged_step",
        "GenerationEngine._pop_doomed_head",
        "GenerationEngine._close_doomed",
        "GenerationEngine._note_kv_step", "GenerationEngine.load_report",
        # the disaggregation paths run on the scheduler threads too:
        # the handoff epilogue, chain adoption, and the cross-engine
        # adopt entry are all host dict/list math — the chain moves
        # page IDS, never page contents
        "GenerationEngine._handoff_seq",
        "GenerationEngine._drain_adopted", "GenerationEngine.adopt",
        # speculative decoding runs entirely on the scheduler thread:
        # draft proposal steps sync k times per iteration (int32s per
        # ready row, marked hot-sync-ok — each feeds the next step's
        # input tokens), the verify verdict reads the per-token lane
        # once, and the rollback/free plumbing is pure host ledger math
        "GenerationEngine._spec_propose",
        "GenerationEngine._spec_rows",
        "GenerationEngine._hist_slice",
        "GenerationEngine._free_draft",
        "GenerationEngine._free_draft_sid",
        "GenerationEngine._release_chain_pair"],
    # speculative decoding config + the acceptance rule: pure host
    # token comparison (the equality contract), no device reads ever
    "paddle_tpu/inference/speculative.py": ["*"],
    # the serving front door: routing decisions and the handoff
    # dispatcher run on caller/scheduler threads against load_report
    # snapshots — pure host scoring, never a device read
    "paddle_tpu/inference/frontdoor.py": ["*"],
    # the serving observatory: request traces mutate on the scheduler
    # hot loop and kvcache snapshots run per step — the whole module
    # must stay pure host arithmetic (no device reads, ever)
    "paddle_tpu/profiler/serve_observatory.py": ["*"],
    # the distributed observatory: collective rollups fold on every
    # collective call and the rankstat cadence check runs per step —
    # the whole module must stay pure host arithmetic (the device-time
    # probe's two deliberate syncs live in jit/api.py, fenced +
    # allowlisted there, NOT here)
    "paddle_tpu/profiler/dist_observatory.py": ["*"],
    # the fleet observatory: journeys complete on the decode
    # scheduler's emit path and fleet snapshots run on submit — the
    # whole module must stay pure host arithmetic (no device reads)
    "paddle_tpu/profiler/fleet_observatory.py": ["*"],
    # the memory observatory: the tag ledger is read on the train-step
    # and decode-scheduler cadences and the OOM forensics run inside
    # dispatch except-blocks — the whole module must stay pure host
    # arithmetic (array .nbytes is metadata, memory_stats() is an
    # allocator query; neither blocks on the device)
    "paddle_tpu/profiler/mem_observatory.py": ["*"],
    # eager collectives are host-visible waits by design, but the
    # instrumentation AROUND them must never add a sync of its own
    "paddle_tpu/distributed/collective.py": [
        "_instrumented", "_payload_bytes", "_any_traced",
        "_group_label"],
    # the pool snapshot is called from the decode loop: dict/len math
    # only, never a device read of the page pools
    "paddle_tpu/ops/paged_attention.py": ["PagedKVCache.pool_stats"],
}

PATTERNS = [
    (re.compile(r"\.item\s*\("), ".item()"),
    (re.compile(r"(?<![\w.])float\s*\("), "float()"),
    (re.compile(r"\.numpy\s*\("), ".numpy()"),
    (re.compile(r"block_until_ready"), "block_until_ready"),
    # np.asarray of a device array is a blocking D2H read — the serving
    # dispatcher idiom (jnp.asarray stays device-side and is NOT matched)
    (re.compile(r"(?<![\w.])np\.asarray\s*\("), "np.asarray()"),
    # jax.device_get is the other blocking D2H idiom (the ragged decode
    # loop's one deliberate sync is marked; anything else is a leak)
    (re.compile(r"device_get\s*\("), "device_get()"),
]

ALLOW_MARKER = "hot-sync-ok"
# the framework grammar's EXPLICITLY-SCOPED spelling of the same
# allowance — both gates (this pass and the shim CLI) honor it, so
# paddlelint and check_no_hot_sync can never disagree on a line. The
# UNSCOPED `# lint-ok:` deliberately does NOT reach the hot-sync
# fence (core.apply_suppressions enforces the same), so a generic
# suppression can't silently blank a sync check.
SCOPED_ALLOW_MARKER = "lint-ok[hot-sync]"


def _named_spans(tree):
    """{qualified name: (first line, last line)} for module-level
    functions and class methods."""
    spans = {}
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            spans[node.name] = (node.lineno, node.end_lineno)
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    spans[f"{node.name}.{sub.name}"] = (sub.lineno,
                                                        sub.end_lineno)
    return spans


# the docstring-line mask (multi-line string constants are not code,
# not linted) — one copy, shared with core.SourceFile.string_lines
_string_lines = string_mask


def check_source(src, names, where, tree=None, skip=None):
    """All violations for one file's source text. `names` is the list of
    hot region names ("*" = whole module). Byte-compatible with the
    historical tools/check_no_hot_sync.py check_source; the framework
    pass forwards its already-parsed `tree`/`skip` so a paddlelint run
    does not re-parse the hot files."""
    violations = []
    if tree is None:
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            return [f"{where}: unparseable ({e})"]
    lines = src.splitlines()
    if skip is None:
        skip = _string_lines(tree)
    if "*" in names:
        regions = [("<module>", 1, len(lines))]
    else:
        spans = _named_spans(tree)
        regions = []
        for name in names:
            if name not in spans:
                violations.append(
                    f"{where}: hot region {name!r} not found — update "
                    "tools/check_no_hot_sync.py HOT_REGIONS")
                continue
            regions.append((name, *spans[name]))
    for name, start, end in regions:
        for ln in range(start, min(end, len(lines)) + 1):
            if ln in skip:
                continue
            line = lines[ln - 1]
            if ALLOW_MARKER in line or SCOPED_ALLOW_MARKER in line:
                continue
            code = line.split("#", 1)[0]
            for pat, label in PATTERNS:
                if pat.search(code):
                    violations.append(
                        f"{where}:{ln}: {label} in hot region {name}: "
                        f"{line.strip()}")
    return violations


def check_repo(repo):
    errors = []
    for rel, names in sorted(HOT_REGIONS.items()):
        path = os.path.join(repo, rel)
        if not os.path.exists(path):
            errors.append(f"{rel}: hot file missing")
            continue
        with open(path) as f:
            errors.extend(check_source(f.read(), names, rel))
    return errors


# -- the framework pass --------------------------------------------------

_VIOLATION_RE = re.compile(r"^(?P<file>[^:]+):(?P<line>\d+): "
                           r"(?P<label>\S+) in hot region "
                           r"(?P<region>\S+): ")


class HotSyncPass:
    """Framework wrapper: the legacy checker's verdicts as Findings,
    plus suppressed findings for every allow-marked line that actually
    matches a sync pattern (the ledger's account of deliberate syncs)."""

    name = PASS_NAME

    def run(self, ctx):
        findings = []
        by_rel = {sf.rel: sf for sf in ctx.files}
        for rel, names in sorted(HOT_REGIONS.items()):
            sf = by_rel.get(rel)
            if sf is None:
                if ctx.root is not None and os.path.exists(
                        os.path.join(ctx.root, rel)):
                    # analyzed set narrower than the region table
                    # (pass-selection run): fall back to disk
                    with open(os.path.join(ctx.root, rel)) as f:
                        src = f.read()
                    try:
                        tree = ast.parse(src)
                    except SyntaxError:
                        tree = None
                    lines, skip = src.splitlines(), \
                        _string_lines(tree) if tree else set()
                else:
                    findings.append(Finding(
                        self.name, "hot-file-missing", rel, 0,
                        "hot file missing — renaming a fenced file "
                        "must move the fence "
                        "(tools/lint/hot_sync.py HOT_REGIONS)"))
                    continue
            else:
                # reuse the ProjectContext's parse (forwarded into
                # check_source below) — no second ast.parse per file
                src, tree = sf.text, sf.tree
                lines, skip = sf.lines, sf.string_lines()
            if tree is None:  # unparseable file: its own rule — a
                # parse failure must not read as a renamed region and
                # send triage to HOT_REGIONS instead of the broken file
                findings.append(Finding(
                    self.name, "hot-file-unparseable", rel, 0,
                    f"unparseable ({sf.parse_error if sf else '?'})"))
                continue
            for v in check_source(src, names, rel, tree=tree,
                                  skip=skip):
                # a real sync verdict matches the `file:line: <label>
                # in hot region` shape; region-gone/unparseable
                # verdicts have no line prefix (classifying on the
                # SHAPE, not the message text — a hot line that
                # happens to contain "not found" stays a sync finding)
                m = _VIOLATION_RE.match(v)
                if m:
                    line, rule = int(m.group("line")), \
                        "sync-in-hot-region"
                elif v.split(": ", 1)[-1].startswith("unparseable ("):
                    line, rule = 0, "hot-file-unparseable"
                else:
                    line, rule = 0, "hot-region-missing"
                msg = v.split(": ", 1)[-1]
                if rule == "hot-region-missing":
                    # check_source's verdict string stays byte-
                    # identical for the shim CLI; the framework
                    # finding points at where the table lives NOW
                    msg = msg.replace("tools/check_no_hot_sync.py",
                                      "tools/lint/hot_sync.py")
                findings.append(Finding(self.name, rule, rel, line,
                                        msg))
            if tree is not None:
                findings.extend(self._allowed_syncs(
                    rel, lines, tree, skip, names))
        return findings

    def _allowed_syncs(self, rel, lines, tree, skip, names):
        """Suppressed findings for allow-marked lines matching a sync
        pattern inside a hot region — every deliberate sync is in the
        ledger with its hot-sync-ok reason."""
        out = []
        if "*" in names:
            regions = [(1, len(lines))]
        else:
            spans = _named_spans(tree)
            regions = [spans[n] for n in names if n in spans]
        from .core import LINT_OK_RE
        seen = set()
        for start, end in regions:
            for ln in range(start, min(end, len(lines)) + 1):
                if ln in skip or ln in seen:
                    continue
                line = lines[ln - 1]
                if ALLOW_MARKER in line:
                    m = HOT_SYNC_OK_RE.search(line)
                elif SCOPED_ALLOW_MARKER in line:
                    m = LINT_OK_RE.search(line)
                else:
                    continue
                reason = m.group("reason").strip() if m else ""
                code = line.split("#", 1)[0]
                for pat, label in PATTERNS:
                    if pat.search(code):
                        seen.add(ln)
                        out.append(Finding(
                            self.name, "sync-in-hot-region", rel, ln,
                            f"{label} in hot region (allow-marked): "
                            f"{line.strip()[:120]}",
                            suppressed=bool(reason),
                            reason=reason or None))
                        break
        return out
