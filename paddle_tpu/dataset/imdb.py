"""paddle.dataset.imdb — aclImdb sentiment corpus, legacy reader API.

Parity: /root/reference/python/paddle/dataset/imdb.py (tar of
aclImdb/{train,test}/{pos,neg}/*.txt; samples are ([word ids], 0|1)).
"""
import collections
import os
import re
import string
import tarfile

from .common import DATA_HOME

__all__ = []


def _tar_path():
    return os.path.join(DATA_HOME, "imdb", "aclImdb_v1.tar.gz")


def tokenize(pattern):
    """Lower-cased, punctuation-stripped token lists from tar members
    whose names match `pattern`."""
    with tarfile.open(_tar_path()) as tarf:
        tf = tarf.next()
        while tf is not None:
            if bool(pattern.match(tf.name)):
                body = tarf.extractfile(tf).read().rstrip(b"\n\r")
                body = body.translate(
                    None, string.punctuation.encode("latin-1"))
                yield body.lower().decode("latin-1").split()
            tf = tarf.next()


def build_dict(pattern, cutoff):
    """Word → id for words with frequency > cutoff, ordered by
    (-freq, word); id len(dict) is reserved for <unk>."""
    word_freq = collections.defaultdict(int)
    for doc in tokenize(pattern):
        for word in doc:
            word_freq[word] += 1
    word_freq = [x for x in word_freq.items() if x[1] > cutoff]
    dictionary = sorted(word_freq, key=lambda x: (-x[1], x[0]))
    words, _ = list(zip(*dictionary))
    word_idx = dict(list(zip(words, range(len(words)))))
    word_idx["<unk>"] = len(words)
    return word_idx


def reader_creator(pos_pattern, neg_pattern, word_idx):
    unk = word_idx["<unk>"]
    all_samples = []

    def load(pattern, label):
        for doc in tokenize(pattern):
            all_samples.append(
                ([word_idx.get(w, unk) for w in doc], label))

    def reader():
        if not all_samples:
            load(pos_pattern, 0)
            load(neg_pattern, 1)
        for sample in all_samples:
            yield sample

    return reader


def train(word_idx):
    """Training reader: ([word ids], 0 for positive / 1 for negative)."""
    return reader_creator(
        re.compile(r"aclImdb/train/pos/.*\.txt$"),
        re.compile(r"aclImdb/train/neg/.*\.txt$"), word_idx)


def test(word_idx):
    return reader_creator(
        re.compile(r"aclImdb/test/pos/.*\.txt$"),
        re.compile(r"aclImdb/test/neg/.*\.txt$"), word_idx)


def word_dict(cutoff=150):
    """Dictionary over the whole corpus (train + test, pos + neg)."""
    return build_dict(re.compile(r"aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$"),
                      cutoff)


def fetch():
    from .common import download
    download("https://dataset.bj.bcebos.com/imdb%2FaclImdb_v1.tar.gz",
             "imdb", None)
