"""paddle.utils.download — weights-cache path resolution.

Parity: /root/reference/python/paddle/utils/download.py. This
environment is zero-egress, so the network half raises a clear
placement instruction; the cache lookup, md5 verification and archive
decompression halves are fully functional against local files.
"""
import hashlib
import os
import os.path as osp
import shutil
import tarfile
import zipfile

__all__ = ["get_weights_path_from_url"]

WEIGHTS_HOME = osp.expanduser("~/.cache/paddle/hapi/weights")
DOWNLOAD_RETRY_LIMIT = 3


def is_url(path):
    return path.startswith(("http://", "https://"))


def _map_path(url, root_dir):
    fname = osp.split(url)[-1]
    return osp.join(root_dir, fname)


def _md5check(fullname, md5sum=None):
    if md5sum is None:
        return True
    md5 = hashlib.md5()
    with open(fullname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            md5.update(chunk)
    return md5.hexdigest() == md5sum


def get_weights_path_from_url(url, md5sum=None):
    """Resolve a weights url to its local cache path (WEIGHTS_HOME)."""
    return get_path_from_url(url, WEIGHTS_HOME, md5sum)


def get_path_from_url(url, root_dir, md5sum=None, check_exist=True,
                      decompress=True):
    if not is_url(url):
        raise ValueError(f"Given url {url} is not valid")
    fullpath = _map_path(url, root_dir)
    if check_exist and osp.exists(fullpath) and _md5check(fullpath,
                                                          md5sum):
        if decompress and (tarfile.is_tarfile(fullpath)
                           or zipfile.is_zipfile(fullpath)):
            return _decompress(fullpath)
        return fullpath
    raise RuntimeError(
        f"zero-egress environment: cannot download {url}; place the "
        f"file at {fullpath} manually")


def _decompress(fname):
    """Unpack a tar/zip next to itself; returns the extracted root."""
    fpath = osp.split(fname)[0]
    if tarfile.is_tarfile(fname):
        with tarfile.open(fname) as f:
            names = f.getnames()
            root = names[0].split("/")[0]
            dst = osp.join(fpath, root)
            if not osp.exists(dst):
                f.extractall(fpath)
        return dst
    if zipfile.is_zipfile(fname):
        with zipfile.ZipFile(fname) as f:
            names = f.namelist()
            root = names[0].split("/")[0]
            dst = osp.join(fpath, root)
            if not osp.exists(dst):
                f.extractall(fpath)
        return dst
    return fname
