"""AST passes converting Python control flow into converter calls.

Parity: the reference's dygraph_to_static transformer stack —
ifelse_transformer.py, loop_transformer.py, logical_transformer.py,
return_transformer.py, call_transformer.py. Same job, different target:
the reference rewrites into ProgramDesc block ops; these passes rewrite
into `_jst.convert_*` runtime calls (convert_operators.py) which lower
onto jax.lax control flow only when the condition is actually traced.

Mechanics: a converted `if`/`while`/`for` body becomes a nested function
that declares `nonlocal` for every name it assigns, plus `__jst_get_N` /
`__jst_set_N` accessors over those names, so the runtime can snapshot,
re-run, and select locals without any frame hacking. Names possibly
undefined before the statement are pre-bound to `_jst.UNDEFINED` through a
`try/except` probe, which both makes `nonlocal` legal and gives loud
use-before-assignment errors.
"""
import ast

__all__ = ["UnsupportedConversion", "apply_transforms", "JST"]

JST = "_jst"  # module alias injected into the exec namespace
_RET = "__jst_ret"
_FLAG = "__jst_did_return"


class UnsupportedConversion(Exception):
    """Raised when a construct cannot be converted; the caller falls back
    to the untransformed function (reference: warn-and-fallback)."""


# ---------------------------------------------------------------- helpers
def _name(id_, ctx=None):
    return ast.Name(id=id_, ctx=ctx or ast.Load())


def _jst_attr(attr):
    return ast.Attribute(value=_name(JST), attr=attr, ctx=ast.Load())


def _jst_call(attr, args):
    return ast.Call(func=_jst_attr(attr), args=args, keywords=[])


def _const(v):
    return ast.Constant(value=v)


def _carry_names(names):
    """Drop transformer-generated helper names (nested converted
    constructs' defs/accessors) from a carry; the return-machinery and
    break/continue flag slots DO carry."""
    return [n for n in names
            if not n.startswith("__jst_") or n in (_RET, _FLAG)
            or n.startswith(("__jst_brk", "__jst_cont", "__jst_fw"))]


def assigned_names(stmts):
    """Names bound by a statement list, NOT descending into nested
    function/class scopes (their assignments are their own locals)."""
    names = set()

    class V(ast.NodeVisitor):
        def visit_Name(self, node):
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                names.add(node.id)

        def visit_Subscript(self, node):
            # a[i] = v: carry `a` so a TENSOR target operates on a fresh
            # re-wrapped Tensor per branch (its jax array is immutable, so
            # snapshot/select is sound). NOTE: mutation of python
            # containers (dict/list) in a tensor-dependent branch is NOT
            # isolated — both branches execute under trace and the object
            # mutates unconditionally; same caveat as the reference's
            # side-effect limitations.
            if isinstance(node.ctx, ast.Store) and isinstance(
                    node.value, ast.Name):
                names.add(node.value.id)
            self.generic_visit(node)

        def visit_FunctionDef(self, node):
            names.add(node.name)  # the def binds its name; skip its body

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_ClassDef(self, node):
            names.add(node.name)

        def visit_Lambda(self, node):
            pass

        def visit_Import(self, node):
            for a in node.names:
                names.add((a.asname or a.name).split(".")[0])

        visit_ImportFrom = visit_Import

        def visit_ExceptHandler(self, node):
            if node.name:
                names.add(node.name)
            self.generic_visit(node)

    v = V()
    for s in stmts:
        v.visit(s)
    return sorted(names)


def _contains(node_or_list, types, *, into_loops=True):
    """Does the subtree contain a node of `types`, not counting nested
    function/class scopes (and optionally not descending into loops)?"""
    found = False

    class V(ast.NodeVisitor):
        def generic_visit(self, node):
            nonlocal found
            if found:
                return
            if isinstance(node, types):
                found = True
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                return
            if not into_loops and isinstance(node, (ast.While, ast.For)):
                return
            super().generic_visit(node)

    nodes = node_or_list if isinstance(node_or_list, list) else [node_or_list]
    for n in nodes:
        V().visit(n)
        if found:
            break
    return found


def _undef_probe(name):
    """try: name \n except (NameError, UnboundLocalError): name = UNDEFINED"""
    return ast.Try(
        body=[ast.Expr(value=_name(name))],
        handlers=[ast.ExceptHandler(
            type=ast.Tuple(elts=[_name("NameError"),
                                 _name("UnboundLocalError")],
                           ctx=ast.Load()),
            name=None,
            body=[ast.Assign(targets=[_name(name, ast.Store())],
                             value=_jst_attr("UNDEFINED"))])],
        orelse=[], finalbody=[])


def _nonlocal_or_pass(names):
    return [ast.Nonlocal(names=list(names))] if names else [ast.Pass()]


def _def(fname, body, args=()):
    return ast.FunctionDef(
        name=fname,
        args=ast.arguments(posonlyargs=[], args=[ast.arg(arg=a)
                                                 for a in args],
                           kwonlyargs=[], kw_defaults=[], defaults=[]),
        body=body, decorator_list=[], returns=None, type_params=[])


def _getter(fname, names):
    return _def(fname, [ast.Return(value=ast.Tuple(
        elts=[_name(n) for n in names], ctx=ast.Load()))])


def _setter(fname, names):
    body = _nonlocal_or_pass(names)
    if names:
        body = [ast.Nonlocal(names=list(names)),
                ast.Assign(
                    targets=[ast.Tuple(elts=[_name(n, ast.Store())
                                             for n in names],
                                       ctx=ast.Store())],
                    value=_name("__jst_vals"))]
    else:
        body = [ast.Pass()]
    return _def(fname, body, args=("__jst_vals",))


# ----------------------------------------------------- return transformer
class ReturnTransformer:
    """Rewrites early returns (returns nested under `if`) into
    `__jst_ret/__jst_did_return` assignments with guarded continuations, so
    a tensor-dependent `if` containing `return` converts cleanly.
    Parity: return_transformer.py. Returns nested inside loops are not
    converted (UnsupportedConversion -> whole-function fallback)."""

    def run(self, fn_node):
        body = fn_node.body
        if not self._has_early_return(body):
            for st in body:  # still recurse into nested defs
                self._recurse_nested(st)
            return fn_node
        new_body, _ = self._block(body)
        fn_node.body = (
            [ast.Assign(targets=[_name(_FLAG, ast.Store())],
                        value=_const(False)),
             ast.Assign(targets=[_name(_RET, ast.Store())],
                        value=_const(None))]
            + new_body
            + [ast.Return(value=_name(_RET))])
        return fn_node

    def _recurse_nested(self, node):
        for child in ast.walk(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and child is not node:
                self.run(child)

    def _has_early_return(self, body):
        for st in body[:-1]:
            if _contains(st, ast.Return):
                return True
        last = body[-1] if body else None
        if last is not None and not isinstance(last, ast.Return) \
                and _contains(last, ast.Return):
            return True
        return False

    def _block(self, stmts):
        """Returns (new_stmts, may_return)."""
        out = []
        for k, st in enumerate(stmts):
            if isinstance(st, ast.Return):
                val = st.value if st.value is not None else _const(None)
                out.append(ast.Assign(
                    targets=[_name(_RET, ast.Store())], value=val))
                out.append(ast.Assign(
                    targets=[_name(_FLAG, ast.Store())], value=_const(True)))
                return out, True  # rest is dead code
            if not _contains(st, ast.Return):
                self._recurse_nested(st)
                out.append(st)
                continue
            if isinstance(st, ast.If):
                b, br = self._block(st.body)
                o, orr = self._block(st.orelse) if st.orelse else ([], False)
                st.body = b
                st.orelse = o
                out.append(st)
                rest, rest_ret = self._block(stmts[k + 1:]) \
                    if k + 1 < len(stmts) else ([], False)
                if rest:
                    guard = ast.If(
                        test=_jst_call("not_returned", [_name(_FLAG)]),
                        body=rest, orelse=[])
                    out.append(guard)
                return out, True
            if isinstance(st, (ast.While, ast.For, ast.Try, ast.With)):
                raise UnsupportedConversion(
                    f"`return` inside a {type(st).__name__.lower()} block "
                    "cannot be converted to graph control flow; hoist the "
                    "return out of the loop")
            raise UnsupportedConversion(
                f"`return` nested in {type(st).__name__}")
        return out, False


class _InterruptRewrite:
    """break/continue -> flag assignments with guarded continuations,
    scoped to ONE loop body (nested loops keep their own interrupts).
    Mirrors ReturnTransformer's guard discipline."""

    def __init__(self, brk, cont):
        self.brk = brk
        self.cont = cont

    def _set(self, name):
        return ast.Assign(targets=[_name(name, ast.Store())],
                          value=_const(True))

    def block(self, stmts):
        """Returns (new_stmts, may_interrupt)."""
        out = []
        for k, st in enumerate(stmts):
            if isinstance(st, ast.Break):
                out.append(self._set(self.brk))
                return out, True  # rest is dead
            if isinstance(st, ast.Continue):
                out.append(self._set(self.cont))
                return out, True
            if not _contains(st, (ast.Break, ast.Continue),
                             into_loops=False):
                out.append(st)
                continue
            if isinstance(st, ast.If):
                b, bi = self.block(st.body)
                o, oi = self.block(st.orelse) if st.orelse else ([], False)
                st.body = b
                st.orelse = o
                out.append(st)
                rest, _ = self.block(stmts[k + 1:]) \
                    if k + 1 < len(stmts) else ([], False)
                if rest:
                    # skip the rest once EITHER flag fired
                    guard = ast.If(
                        test=_jst_call("not_interrupted",
                                       [_name(self.brk),
                                        _name(self.cont)]),
                        body=rest, orelse=[])
                    out.append(guard)
                return out, True
            raise UnsupportedConversion(
                f"break/continue nested in {type(st).__name__} inside a "
                "converted loop")
        return out, False


# ----------------------------------------- control-flow (stmt) transformer
class ControlFlowTransformer(ast.NodeTransformer):
    """Rewrites If/While/For statements into `_jst.convert_*` dispatch.
    Parity: ifelse_transformer.py + loop_transformer.py."""

    def __init__(self):
        self._n = 0

    def _uid(self):
        self._n += 1
        return self._n

    # Leave nested scopes' internals to their own visit (mechanics are
    # scope-local, so plain recursion is correct).

    def _convert_block(self, stmts):
        out = []
        for st in stmts:
            r = self.visit(st)
            out.extend(r if isinstance(r, list) else [r])
        return out or [ast.Pass()]

    def visit_FunctionDef(self, node):
        node.body = self._convert_block(node.body)
        return node

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_If(self, node):
        uid = self._uid()
        body = self._convert_block(node.body)
        orelse = self._convert_block(node.orelse) if node.orelse \
            else [ast.Pass()]
        names = _carry_names(assigned_names(body + orelse))
        t, f = f"__jst_true_{uid}", f"__jst_false_{uid}"
        g, s = f"__jst_get_{uid}", f"__jst_set_{uid}"
        stmts = [_undef_probe(n) for n in names]
        stmts.append(_def(t, _nonlocal_or_pass(names) + body))
        stmts.append(_def(f, _nonlocal_or_pass(names) + orelse))
        stmts.append(_getter(g, names))
        stmts.append(_setter(s, names))
        stmts.append(ast.Expr(value=_jst_call(
            "convert_ifelse",
            [node.test, _name(t), _name(f), _name(g), _name(s),
             ast.Tuple(elts=[_const(n) for n in names], ctx=ast.Load())])))
        for st in stmts:
            ast.copy_location(st, node)
        return stmts

    def _for_to_while(self, node):
        """`for TGT in X: BODY` -> counter-while with TGT bound per
        iteration; X is either range(...) (counter IS the target source)
        or a sequence (indexed per iteration)."""
        uid = self._uid()
        i_n = f"__jst_fwi_{uid}"
        it = node.iter
        pre = []
        starred = (isinstance(it, ast.Call)
                   and any(isinstance(a, ast.Starred) for a in it.args))
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Name) \
                and it.func.id == "range" and not it.keywords \
                and not starred:
            a = list(it.args)
            start = a[0] if len(a) >= 2 else _const(0)
            stop = a[1] if len(a) >= 2 else a[0]
            step = a[2] if len(a) >= 3 else _const(1)
            stop_n, step_n = f"__jst_fws_{uid}", f"__jst_fwp_{uid}"
            pre = [ast.Assign(targets=[_name(i_n, ast.Store())],
                              value=start),
                   ast.Assign(targets=[_name(stop_n, ast.Store())],
                              value=stop),
                   ast.Assign(targets=[_name(step_n, ast.Store())],
                              value=step)]
            test = _jst_call("range_continues",
                             [_name(i_n), _name(stop_n), _name(step_n)])
            bind = ast.Assign(targets=[node.target], value=_name(i_n))
            bump = ast.AugAssign(target=_name(i_n, ast.Store()),
                                 op=ast.Add(), value=_name(step_n))
        else:
            seq_n = f"__jst_fwq_{uid}"
            # materialize one-shot iterables (zip/generators); a range
            # object from range(*args) passes through (len+getitem work)
            pre = [ast.Assign(targets=[_name(seq_n, ast.Store())],
                              value=_jst_call("materialize_seq", [it])),
                   ast.Assign(targets=[_name(i_n, ast.Store())],
                              value=_const(0))]
            test = _jst_call("seq_continues", [_name(i_n), _name(seq_n)])
            bind = ast.Assign(
                targets=[node.target],
                value=_jst_call("seq_get", [_name(seq_n), _name(i_n)]))
            bump = ast.AugAssign(target=_name(i_n, ast.Store()),
                                 op=ast.Add(), value=_const(1))
        # bind + bump run BEFORE the body: `continue` must still advance
        # the counter (Python for semantics), and the interrupt rewrite
        # only guards statements after the continue site
        w = ast.While(test=test, body=[bind, bump] + node.body,
                      orelse=[])
        mod = ast.Module(body=pre + [w], type_ignores=[])
        for st in ast.walk(mod):
            ast.copy_location(st, node)
        # the caller visits the returned statements; hand back the list
        out = list(pre)
        r = self.visit(w)
        out.extend(r if isinstance(r, list) else [r])
        return out

    def visit_While(self, node):
        if node.orelse:
            # while/else: leave as Python (eager works; a traced
            # condition will fail loudly at the bool() coercion)
            node.body = self._convert_block(node.body)
            return node
        uid = self._uid()
        test = node.test
        pre = []
        raw_body = node.body
        if _contains(raw_body, (ast.Break, ast.Continue),
                     into_loops=False):
            # break/continue become flags (ref loop_transformer.py):
            #   break    -> __jst_brk_N = True  (+ guards on the rest)
            #   continue -> __jst_cont_N = True (reset each iteration)
            # and the loop condition gains `and not __jst_brk_N`
            brk, cont = f"__jst_brk_{uid}", f"__jst_cont_{uid}"
            raw_body, _ = _InterruptRewrite(brk, cont).block(raw_body)
            raw_body = [ast.Assign(targets=[_name(cont, ast.Store())],
                                   value=_const(False))] + raw_body
            pre = [ast.Assign(targets=[_name(brk, ast.Store())],
                              value=_const(False))]
            thunk = ast.Lambda(
                args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                                   kw_defaults=[], defaults=[]),
                body=test)
            test = _jst_call("convert_logical_and",
                             [_jst_call("convert_logical_not",
                                        [_name(brk)]), thunk])
        body = self._convert_block(raw_body)
        names = _carry_names(assigned_names(body))
        c, b = f"__jst_cond_{uid}", f"__jst_body_{uid}"
        g, s = f"__jst_get_{uid}", f"__jst_set_{uid}"
        stmts = pre + [_undef_probe(n) for n in names]
        stmts.append(_def(c, [ast.Return(value=test)]))
        stmts.append(_def(b, _nonlocal_or_pass(names) + body))
        stmts.append(_getter(g, names))
        stmts.append(_setter(s, names))
        stmts.append(ast.Expr(value=_jst_call(
            "convert_while_loop",
            [_name(c), _name(b), _name(g), _name(s)])))
        for st in stmts:
            ast.copy_location(st, node)
        return stmts

    def visit_For(self, node):
        if node.orelse:
            node.body = self._convert_block(node.body)
            return node
        if _contains(node.body, (ast.Break, ast.Continue),
                     into_loops=False):
            # desugar to a while loop (counter + explicit target bind) so
            # the while machinery's interrupt-flag lowering applies
            # (ref loop_transformer.py for->while normalization)
            return self._for_to_while(node)
        uid = self._uid()
        body = self._convert_block(node.body)
        # loop-target names are assigned by iteration itself
        tgt_names = assigned_names([ast.Assign(
            targets=[node.target], value=_const(None))])
        names = _carry_names(
            sorted(set(assigned_names(body)) | set(tgt_names)))
        ts, b = f"__jst_tgt_{uid}", f"__jst_body_{uid}"
        g, s = f"__jst_get_{uid}", f"__jst_set_{uid}"
        stmts = [_undef_probe(n) for n in names]
        # def __jst_tgt(v): nonlocal <tgts>; <target> = v
        tgt_assign = ast.Assign(targets=[node.target],
                                value=_name("__jst_vals"))
        stmts.append(_def(ts, [ast.Nonlocal(names=list(tgt_names)),
                               tgt_assign] if tgt_names else [ast.Pass()],
                          args=("__jst_vals",)))
        stmts.append(_def(b, _nonlocal_or_pass(names) + body))
        stmts.append(_getter(g, names))
        stmts.append(_setter(s, names))
        it = node.iter
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Name) \
                and it.func.id == "range" and not it.keywords:
            call = _jst_call("convert_for_range",
                             [ast.Tuple(elts=list(it.args), ctx=ast.Load()),
                              _name(ts), _name(b), _name(g), _name(s)])
        else:
            call = _jst_call("convert_for",
                             [it, _name(ts), _name(b), _name(g), _name(s)])
        stmts.append(ast.Expr(value=call))
        for st in stmts:
            ast.copy_location(st, node)
        return stmts


# ------------------------------------------- expression-level transformer
class ExprTransformer(ast.NodeTransformer):
    """BoolOp / Not / IfExp / Call conversion.
    Parity: logical_transformer.py + call_transformer.py."""

    def visit_BoolOp(self, node):
        self.generic_visit(node)
        vals = node.values
        attr = "convert_logical_and" if isinstance(node.op, ast.And) \
            else "convert_logical_or"
        expr = vals[0]
        for v in vals[1:]:
            thunk = ast.Lambda(
                args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                                   kw_defaults=[], defaults=[]),
                body=v)
            expr = _jst_call(attr, [expr, thunk])
        return ast.copy_location(expr, node)

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.copy_location(
                _jst_call("convert_logical_not", [node.operand]), node)
        return node

    def visit_IfExp(self, node):
        self.generic_visit(node)
        mk = lambda b: ast.Lambda(
            args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                               kw_defaults=[], defaults=[]),
            body=b)
        return ast.copy_location(
            _jst_call("convert_ifexp",
                      [node.test, mk(node.body), mk(node.orelse)]), node)

    def visit_Assert(self, node):
        self.generic_visit(node)
        args = [node.test] + ([node.msg] if node.msg is not None else [])
        return ast.copy_location(
            ast.Expr(value=_jst_call("convert_assert", args)), node)

    def visit_Call(self, node):
        self.generic_visit(node)
        f = node.func
        if isinstance(f, ast.Name) and f.id == "print":
            node.func = ast.copy_location(_jst_attr("convert_print"), f)
            return node
        if isinstance(f, ast.Name) and (
                f.id.startswith("__jst_") or f.id in ("super", "locals",
                                                      "globals", "range")):
            return node
        if isinstance(f, ast.Attribute):
            root = f
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name) and root.id == JST:
                return node
            # method calls (x.foo()) pass through: bound methods of
            # framework objects dominate; user functions are almost always
            # called by bare name
            return node
        if isinstance(f, ast.Name):
            node.func = ast.copy_location(
                _jst_call("convert_call", [f]), f)
        return node


def apply_transforms(fn_node):
    """Run the full pass pipeline over one FunctionDef."""
    for sub in ast.walk(fn_node):
        if isinstance(sub, (ast.Global,)):
            raise UnsupportedConversion("`global` declarations")
    ReturnTransformer().run(fn_node)
    ControlFlowTransformer().visit(fn_node)
    ExprTransformer().visit(fn_node)
    ast.fix_missing_locations(fn_node)
    return fn_node
