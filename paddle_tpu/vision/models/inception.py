"""GoogLeNet (Inception v1) + InceptionV3. Parity:
python/paddle/vision/models/{googlenet,inceptionv3}.py.

Multi-branch inception blocks: each branch is conv+BN+ReLU; branch
outputs concat on channels. GoogLeNet keeps the reference's 3-output
contract (main logits + two aux heads).
"""
from ... import nn
from ...tensor.manipulation import concat, flatten

__all__ = ["GoogLeNet", "googlenet", "InceptionV3", "inception_v3"]


class _ConvBN(nn.Layer):
    def __init__(self, in_c, out_c, k, stride=1, padding=0):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, k, stride=stride,
                              padding=padding, bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.relu(self.bn(self.conv(x)))


# ---------------------------------------------------------------- GoogLeNet
class _Inception(nn.Layer):
    """v1 inception block (ref: vision/models/googlenet.py:66)."""

    def __init__(self, in_c, f1, f3r, f3, f5r, f5, proj):
        super().__init__()
        self.b1 = _ConvBN(in_c, f1, 1)
        self.b3 = nn.Sequential(_ConvBN(in_c, f3r, 1),
                                _ConvBN(f3r, f3, 3, padding=1))
        self.b5 = nn.Sequential(_ConvBN(in_c, f5r, 1),
                                _ConvBN(f5r, f5, 5, padding=2))
        self.pool = nn.MaxPool2D(3, stride=1, padding=1)
        self.proj = _ConvBN(in_c, proj, 1)

    def forward(self, x):
        return concat([self.b1(x), self.b3(x), self.b5(x),
                       self.proj(self.pool(x))], axis=1)


class GoogLeNet(nn.Layer):
    """GoogLeNet (ref: vision/models/googlenet.py:97). forward returns
    (main_logits, aux1_logits, aux2_logits) like the reference."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _ConvBN(3, 64, 7, stride=2, padding=3),
            nn.MaxPool2D(3, stride=2, padding=1),
            _ConvBN(64, 64, 1),
            _ConvBN(64, 192, 3, padding=1),
            nn.MaxPool2D(3, stride=2, padding=1))
        self.inc3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.inc3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, stride=2, padding=1)
        self.inc4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.inc4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.inc4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.inc4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.inc4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, stride=2, padding=1)
        self.inc5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.inc5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.dropout = nn.Dropout(0.2)
            self.fc = nn.Linear(1024, num_classes)
            # aux classifiers (active in train and eval, as in reference)
            self.aux1 = _AuxHead(512, num_classes)
            self.aux2 = _AuxHead(528, num_classes)

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.inc3b(self.inc3a(x)))
        x = self.inc4a(x)
        a1 = x
        x = self.inc4c(self.inc4b(x))
        x = self.inc4d(x)
        a2 = x
        x = self.pool4(self.inc4e(x))
        x = self.inc5b(self.inc5a(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes <= 0:
            return x
        out = self.fc(self.dropout(flatten(x, 1)))
        return out, self.aux1(a1), self.aux2(a2)


class _AuxHead(nn.Layer):
    def __init__(self, in_c, num_classes):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D((4, 4))
        self.conv = _ConvBN(in_c, 128, 1)
        self.fc1 = nn.Linear(128 * 16, 1024)
        self.relu = nn.ReLU()
        self.dropout = nn.Dropout(0.7)
        self.fc2 = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = flatten(self.conv(self.pool(x)), 1)
        return self.fc2(self.dropout(self.relu(self.fc1(x))))


def googlenet(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights require network access; load a local "
            "state_dict via model.set_state_dict instead")
    return GoogLeNet(**kwargs)


# -------------------------------------------------------------- InceptionV3
class _InceptionA(nn.Layer):
    def __init__(self, in_c, pool_features):
        super().__init__()
        self.b1 = _ConvBN(in_c, 64, 1)
        self.b5 = nn.Sequential(_ConvBN(in_c, 48, 1),
                                _ConvBN(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(_ConvBN(in_c, 64, 1),
                                _ConvBN(64, 96, 3, padding=1),
                                _ConvBN(96, 96, 3, padding=1))
        self.pool = nn.AvgPool2D(3, stride=1, padding=1)
        self.proj = _ConvBN(in_c, pool_features, 1)

    def forward(self, x):
        return concat([self.b1(x), self.b5(x), self.b3(x),
                       self.proj(self.pool(x))], axis=1)


class _InceptionB(nn.Layer):
    """grid reduction 35 -> 17"""

    def __init__(self, in_c):
        super().__init__()
        self.b3 = _ConvBN(in_c, 384, 3, stride=2)
        self.b3dbl = nn.Sequential(_ConvBN(in_c, 64, 1),
                                   _ConvBN(64, 96, 3, padding=1),
                                   _ConvBN(96, 96, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return concat([self.b3(x), self.b3dbl(x), self.pool(x)], axis=1)


class _InceptionC(nn.Layer):
    def __init__(self, in_c, c7):
        super().__init__()
        self.b1 = _ConvBN(in_c, 192, 1)
        self.b7 = nn.Sequential(
            _ConvBN(in_c, c7, 1),
            _ConvBN(c7, c7, (1, 7), padding=(0, 3)),
            _ConvBN(c7, 192, (7, 1), padding=(3, 0)))
        self.b7dbl = nn.Sequential(
            _ConvBN(in_c, c7, 1),
            _ConvBN(c7, c7, (7, 1), padding=(3, 0)),
            _ConvBN(c7, c7, (1, 7), padding=(0, 3)),
            _ConvBN(c7, c7, (7, 1), padding=(3, 0)),
            _ConvBN(c7, 192, (1, 7), padding=(0, 3)))
        self.pool = nn.AvgPool2D(3, stride=1, padding=1)
        self.proj = _ConvBN(in_c, 192, 1)

    def forward(self, x):
        return concat([self.b1(x), self.b7(x), self.b7dbl(x),
                       self.proj(self.pool(x))], axis=1)


class _InceptionD(nn.Layer):
    """grid reduction 17 -> 8"""

    def __init__(self, in_c):
        super().__init__()
        self.b3 = nn.Sequential(_ConvBN(in_c, 192, 1),
                                _ConvBN(192, 320, 3, stride=2))
        self.b7x3 = nn.Sequential(
            _ConvBN(in_c, 192, 1),
            _ConvBN(192, 192, (1, 7), padding=(0, 3)),
            _ConvBN(192, 192, (7, 1), padding=(3, 0)),
            _ConvBN(192, 192, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return concat([self.b3(x), self.b7x3(x), self.pool(x)], axis=1)


class _InceptionE(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b1 = _ConvBN(in_c, 320, 1)
        self.b3_stem = _ConvBN(in_c, 384, 1)
        self.b3_a = _ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b3_b = _ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.b3dbl_stem = nn.Sequential(_ConvBN(in_c, 448, 1),
                                        _ConvBN(448, 384, 3, padding=1))
        self.b3dbl_a = _ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b3dbl_b = _ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.pool = nn.AvgPool2D(3, stride=1, padding=1)
        self.proj = _ConvBN(in_c, 192, 1)

    def forward(self, x):
        b3 = self.b3_stem(x)
        b3 = concat([self.b3_a(b3), self.b3_b(b3)], axis=1)
        d = self.b3dbl_stem(x)
        d = concat([self.b3dbl_a(d), self.b3dbl_b(d)], axis=1)
        return concat([self.b1(x), b3, d, self.proj(self.pool(x))],
                      axis=1)


class InceptionV3(nn.Layer):
    """Inception v3 (ref: vision/models/inceptionv3.py:433)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _ConvBN(3, 32, 3, stride=2),
            _ConvBN(32, 32, 3),
            _ConvBN(32, 64, 3, padding=1),
            nn.MaxPool2D(3, stride=2),
            _ConvBN(64, 80, 1),
            _ConvBN(80, 192, 3),
            nn.MaxPool2D(3, stride=2))
        self.blocks = nn.Sequential(
            _InceptionA(192, 32), _InceptionA(256, 64),
            _InceptionA(288, 64),
            _InceptionB(288),
            _InceptionC(768, 128), _InceptionC(768, 160),
            _InceptionC(768, 160), _InceptionC(768, 192),
            _InceptionD(768),
            _InceptionE(1280), _InceptionE(2048))
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.dropout = nn.Dropout(0.2)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(flatten(x, 1)))
        return x


def inception_v3(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights require network access; load a local "
            "state_dict via model.set_state_dict instead")
    return InceptionV3(**kwargs)
