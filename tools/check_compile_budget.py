#!/usr/bin/env python
"""Compile-budget gate: lower+compile seconds per executable, ratcheted
against the checked-in BASELINE_HLO.json.

Why: the headline bench has died five rounds in a row inside "stage:
compile" with no per-executable attribution (ROADMAP open item 3). The
compilation observatory (profiler/compile_observatory.py) now records
where every compile second goes; this gate turns those records into a
CI fence — a change that makes an executable meaningfully slower to
lower+compile fails loudly, named, before it ever reaches a 300 s TPU
timeout.

Comparison: per baseline tag, FAIL when the tag's SLOWEST single
compile (a real ledger legitimately carries several signatures per tag
— tail batch, eval dtype — and N ordinary compiles must not sum into a
fake regression) exceeds its budget:

    max over signatures (lower_s+compile_s)  >  base total_s * FACTOR
                                                + SLACK

Warm-set wall clock: when the ledger carries a `kind:"warm"` record
(jit/warm.join — the canonical workload always emits one), its wall_s
— the wall-clock of compiling the WHOLE warm set through the
background compile pipeline — is compared against the baseline's
`warm_set` entry under the same FACTOR/SLACK budget. This is the
overlap fence: per-executable seconds can all stay green while a
serialization bug (a lost worker pool, a global lock around the XLA
compile) quietly turns the warm set's wall back into the sum; the
wall comparand catches exactly that. `--update` ratchets it like any
other entry (only ever faster).

FACTOR (default 2.5) and SLACK (default 2.0 s) absorb host-load noise
on the 2-CPU container — compile WALL time is load-sensitive, so the
budget is deliberately generous; a real regression (a new unrolled
layer body, a lost scan) blows through multiples, not percents.

Sources (first match wins):
  --ledger FILE.jsonl   kind:"compile" records from any metrics JSONL
                        (e.g. a bench run's PADDLE_TPU_METRICS_FILE)
  (default)             run the canonical workload (tools/_gate_common
                        --emit) in a clean subprocess and gate that

Ratchet: `--update` rewrites a baseline entry only when the current run
is FASTER (and records new, unbudgeted tags); the gate itself never
loosens the baseline. tests/test_compile_observatory.py runs this gate
from tier-1: green on the checked-in baseline, nonzero (naming the
executable) on an injected regression.

Usage:
  python tools/check_compile_budget.py [--baseline BASELINE_HLO.json]
         [--ledger FILE.jsonl] [--factor 2.5] [--slack 2.0]
         [--require-all] [--update]
Exit 0 within budget, 1 on regression, 2 on gate failure.
"""
import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import _gate_common as gc  # noqa: E402


def compare(baseline, current, factor, slack, require_all):
    """(violations, notes, ratchet) — ratchet maps tag -> better entry."""
    violations, notes, ratchet = [], [], {}
    base_tags = baseline["executables"]
    for tag in sorted(base_tags):
        base = base_tags[tag]
        cur = current.get(tag)
        if cur is None:
            msg = (f"{tag}: in baseline but not in the ledger (renamed "
                   "executable? partial ledger?)")
            (violations if require_all else notes).append(msg)
            continue
        base_total = float(base.get("total_s",
                                    base.get("lower_s", 0.0)
                                    + base.get("compile_s", 0.0)))
        budget = base_total * factor + slack
        cur_total = cur["total_s"]
        if cur_total > budget:
            violations.append(
                f"{tag}: slowest lower+compile {cur_total:.2f}s exceeds "
                f"budget {budget:.2f}s (baseline {base_total:.2f}s "
                f"x{factor} + {slack}s slack) — attack the compile, "
                "don't raise the budget")
        elif cur_total < base_total:
            ratchet[tag] = cur
            notes.append(f"{tag}: {cur_total:.2f}s beats baseline "
                         f"{base_total:.2f}s (ratchet with --update)")
    for tag in sorted(set(current) - set(base_tags)):
        notes.append(f"{tag}: new executable with no budget "
                     f"({current[tag]['total_s']:.2f}s) — add it with "
                     "--update")
        ratchet[tag] = current[tag]
    return violations, notes, ratchet


def compare_warm(baseline, warm_rec, factor, slack, require_all):
    """(violations, notes, ratchet_entry_or_None) for the warm-set
    wall-clock comparand."""
    violations, notes = [], []
    base = baseline.get("warm_set")
    if warm_rec is None:
        msg = ("warm_set: in baseline but the ledger has no "
               "kind:'warm' record (pre-warm-pipeline ledger?)")
        if base is not None:
            (violations if require_all else notes).append(msg)
        return violations, notes, None
    wall = float(warm_rec.get("wall_s", 0.0))
    entry = {"wall_s": round(wall, 3),
             "sum_s": round(float(warm_rec.get("sum_s", 0.0)), 3),
             "n_executables": int(warm_rec.get("n_executables", 0))}
    if base is None:
        notes.append(f"warm_set: no baseline (wall {wall:.2f}s) — add "
                     "it with --update")
        return violations, notes, entry
    base_wall = float(base.get("wall_s", 0.0))
    budget = base_wall * factor + slack
    if wall > budget:
        violations.append(
            f"warm_set: wall-clock {wall:.2f}s for "
            f"{entry['n_executables']} executables exceeds budget "
            f"{budget:.2f}s (baseline {base_wall:.2f}s x{factor} + "
            f"{slack}s slack) — the background compile overlap broke "
            "(serialized compiles?); restore the overlap, don't raise "
            "the budget")
        return violations, notes, None
    if wall < base_wall:
        if entry["wall_s"] >= entry["sum_s"]:
            # a single-worker box (1 CPU) serializes compiles: wall ~=
            # sum. Its faster absolute wall must NOT replace checked-in
            # OVERLAP evidence (wall well under sum) — the comparand
            # exists to catch the overlap breaking, and a wall>=sum
            # baseline could never catch it again.
            notes.append(
                f"warm_set: wall {wall:.2f}s beats baseline "
                f"{base_wall:.2f}s but carries no overlap evidence "
                f"(wall >= sum {entry['sum_s']:.2f}s — serialized "
                "compiles); keeping the checked-in evidence")
            return violations, notes, None
        notes.append(f"warm_set: wall {wall:.2f}s beats baseline "
                     f"{base_wall:.2f}s (ratchet with --update)")
        return violations, notes, entry
    return violations, notes, None


def _entry(cur, base=None):
    """Ratchet entry: rewrite ONLY this gate's comparands (the
    seconds). fusion/bytes/instructions stay whatever check_fusion last
    ratcheted — a faster compile must not launder a concurrent fusion
    regression into the shared baseline. A NEW tag (no base) records
    the full row so both gates have something to compare next run."""
    entry = dict(base or {})
    entry.update({"lower_s": round(cur["lower_s"], 3),
                  "compile_s": round(cur["compile_s"], 3),
                  "total_s": round(cur["total_s"], 3)})
    if base is None:
        entry.update({"fusion_count": int(cur["fusion_count"]),
                      "bytes_accessed": float(cur["bytes_accessed"]),
                      "instructions": int(cur["instructions"]),
                      "flops": float(cur["flops"])})
    return entry


def main(argv=None):
    ap = argparse.ArgumentParser(
        "check_compile_budget",
        description="per-executable lower+compile seconds vs "
                    "BASELINE_HLO.json")
    ap.add_argument("--baseline", default=gc.BASELINE_DEFAULT)
    ap.add_argument("--ledger", default=None,
                    help="metrics JSONL with kind:'compile' records; "
                         "default: run the canonical workload")
    ap.add_argument("--factor", type=float, default=float(
        os.environ.get("PADDLE_TPU_COMPILE_BUDGET_FACTOR", "2.5")))
    ap.add_argument("--slack", type=float, default=float(
        os.environ.get("PADDLE_TPU_COMPILE_BUDGET_SLACK", "2.0")))
    ap.add_argument("--require-all", action="store_true",
                    help="every baseline executable must appear in the "
                         "ledger (canonical-workload ledgers)")
    ap.add_argument("--update", action="store_true",
                    help="ratchet: rewrite baseline entries the current "
                         "run beats; add unbudgeted tags")
    args = ap.parse_args(argv)

    try:
        baseline = gc.load_baseline(args.baseline)
        if args.ledger:
            current = gc.aggregate(
                gc.load_compile_records(args.ledger))
            warm_rec = gc.load_warm_record(args.ledger)
        else:
            with tempfile.TemporaryDirectory() as td:
                ledger_path = os.path.join(td, "ledger.jsonl")
                current = gc.run_workload(ledger_path)
                warm_rec = gc.load_warm_record(ledger_path)
    except (gc.GateError, OSError) as e:
        print(f"check_compile_budget: {e}", file=sys.stderr)
        return 2

    violations, notes, ratchet = compare(
        baseline, current, args.factor, args.slack, args.require_all)
    w_viol, w_notes, w_entry = compare_warm(
        baseline, warm_rec, args.factor, args.slack, args.require_all)
    violations += w_viol
    notes += w_notes

    print("compile budget (lower+compile seconds per executable):")
    for tag in sorted(current):
        cur = current[tag]
        base = baseline["executables"].get(tag, {})
        base_s = base.get("total_s")
        print(gc.format_row(tag, [
            f"now {cur['total_s']:7.2f}s",
            f"base {base_s:7.2f}s" if base_s is not None
            else "base    none",
            "hit" if cur["cache_hit"] else "cold"]))
    if warm_rec is not None:
        base_w = (baseline.get("warm_set") or {}).get("wall_s")
        print(gc.format_row("warm_set (wall-clock)", [
            f"now {float(warm_rec.get('wall_s', 0.0)):7.2f}s",
            f"base {base_w:7.2f}s" if base_w is not None
            else "base    none",
            f"sum {float(warm_rec.get('sum_s', 0.0)):.2f}s"]))
    for n in notes:
        print(f"note: {n}")
    if args.update and (ratchet or w_entry):
        for tag, cur in ratchet.items():
            baseline["executables"][tag] = _entry(
                cur, baseline["executables"].get(tag))
        if w_entry:
            baseline["warm_set"] = w_entry
        gc.save_baseline(args.baseline, baseline)
        print(f"ratcheted {len(ratchet) + bool(w_entry)} entr(y/ies) "
              f"-> {args.baseline}")
    for v in violations:
        print(f"FAIL: {v}")
    if violations:
        print(f"FAIL: {len(violations)} compile-budget regression(s)")
        return 1
    print(f"OK: {len(current)} executable(s) within compile budget")
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
